"""Consul Connect service mesh: sidecar injection admission hook,
NOMAD_UPSTREAM_ADDR env contract, upstream resolution, and the L4
sidecar proxy forwarding real TCP (reference model:
nomad/job_endpoint_hooks connect hook + command/agent/consul connect
tests; envoybootstrap hook replaced by the in-tree forwarder).
"""
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

from nomad_tpu import jobspec, mock
from nomad_tpu.server import Server
from nomad_tpu.structs import (
    ConnectUpstream,
    ConsulConnect,
    Service,
)

HCL_CONNECT = """
job "mesh" {
  datacenters = ["dc1"]

  group "api" {
    count = 1
    task "server" {
      driver = "mock_driver"
      config { run_for = "60s" }
      service {
        name = "api"
        port = "8080"
        connect {
          sidecar_service {}
        }
      }
    }
  }

  group "web" {
    count = 1
    task "frontend" {
      driver = "mock_driver"
      config { run_for = "60s" }
      service {
        name = "web"
        port = "9090"
        connect {
          sidecar_service {
            proxy {
              upstreams {
                destination_name = "api"
                local_bind_port  = 8081
              }
            }
          }
        }
      }
    }
  }
}
"""


def test_jobspec_parses_connect_stanza():
    job = jobspec.parse(HCL_CONNECT)
    web = job.task_groups[1]
    svc = web.tasks[0].services[0]
    assert svc.name == "web"
    assert svc.connect is not None
    assert svc.connect.sidecar_service
    assert svc.connect.upstreams[0].destination_name == "api"
    assert svc.connect.upstreams[0].local_bind_port == 8081


def test_connect_sidecar_injection():
    """Registering a connect job injects the proxy task and the
    NOMAD_UPSTREAM_ADDR env (reference jobConnectHook)."""
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=2)
    try:
        job = jobspec.parse(HCL_CONNECT)
        server.register_node(mock.node())
        server.register_job(job)
        stored = server.store.job_by_id("default", "mesh")
        web = stored.lookup_task_group("web")
        names = [t.name for t in web.tasks]
        assert "connect-proxy-web" in names, names
        proxy = next(
            t for t in web.tasks if t.name == "connect-proxy-web"
        )
        assert proxy.lifecycle is not None and proxy.lifecycle.sidecar
        assert proxy.config["connect_upstreams"] == [["api", 8081]]
        # app task sees the local bind address
        app = next(t for t in web.tasks if t.name == "frontend")
        assert (
            app.env.get("NOMAD_UPSTREAM_ADDR_API") == "127.0.0.1:8081"
        )
        # idempotent on re-register
        server.register_job(jobspec.parse(HCL_CONNECT))
        stored2 = server.store.job_by_id("default", "mesh")
        names2 = [
            t.name for t in stored2.lookup_task_group("web").tasks
        ]
        assert names2.count("connect-proxy-web") == 1
    finally:
        server.stop()


def test_upstream_resolution_from_catalog():
    """The task runner resolves NOMAD_CONNECT_TARGET_* from the
    service catalog at launch."""
    from nomad_tpu.client.task_runner import TaskRunner

    class FakeCatalog:
        def instances(self, name, healthy_only=False):
            class I:
                address = "10.1.2.3"
                port = 4411

            return [I()] if name == "api" else []

    tr = TaskRunner.__new__(TaskRunner)
    tr.catalog = FakeCatalog()
    assert tr._resolve_upstream("api") == "10.1.2.3:4411"
    assert tr._resolve_upstream("ghost") == ""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_connect_proxy_forwards_tcp():
    """The sidecar forwarder moves real bytes: client -> local bind ->
    resolved upstream target."""
    # upstream echo server
    upstream = socket.socket()
    upstream.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    upstream.bind(("127.0.0.1", 0))
    upstream.listen(1)
    up_port = upstream.getsockname()[1]

    def echo():
        conn, _ = upstream.accept()
        data = conn.recv(1024)
        conn.sendall(b"echo:" + data)
        conn.close()

    threading.Thread(target=echo, daemon=True).start()

    bind_port = _free_port()
    env = dict(os.environ)
    env["NOMAD_CONNECT_TARGET_API"] = f"127.0.0.1:{up_port}"
    proxy = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "nomad_tpu.client.connect",
            "--upstream",
            f"api:{bind_port}",
        ],
        env=env,
    )
    try:
        deadline = time.monotonic() + 10
        last = None
        while time.monotonic() < deadline:
            try:
                c = socket.create_connection(
                    ("127.0.0.1", bind_port), timeout=2
                )
                break
            except OSError as exc:
                last = exc
                time.sleep(0.1)
        else:
            pytest.fail(f"proxy never bound: {last}")
        c.sendall(b"hello-mesh")
        got = c.recv(1024)
        assert got == b"echo:hello-mesh"
        c.close()
    finally:
        proxy.kill()
        upstream.close()


@pytest.mark.slow
def test_connect_end_to_end_through_client():
    """Full path: api group serves TCP, web group's injected sidecar
    proxies to it via catalog resolution; the web task reaches the api
    through its local bind."""
    import tempfile

    from nomad_tpu.client.client import Client

    data = tempfile.mkdtemp(prefix="connect-e2e-")
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=9)
    server.start()
    client = Client(
        server,
        node=mock.node(),
        data_dir=data,
        fingerprint=False,
        heartbeat_interval=5.0,
    )
    client.start()
    try:
        # a real TCP service to stand in for the api alloc's task
        api_sock = socket.socket()
        api_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        api_sock.bind(("127.0.0.1", 0))
        api_sock.listen(4)
        api_port = api_sock.getsockname()[1]

        def serve():
            while True:
                try:
                    conn, _ = api_sock.accept()
                except OSError:
                    return
                conn.sendall(b"api-ok")
                conn.close()

        threading.Thread(target=serve, daemon=True).start()

        bind_port = _free_port()
        # api group: a plain connect service backed by the socket above
        api_job = mock.job(id="mesh-api")
        api_job.task_groups[0].count = 1
        at = api_job.task_groups[0].tasks[0]
        at.driver = "mock_driver"
        at.config = {"run_for": 60}
        at.services = [
            Service(
                name="api-svc",
                port_label=str(api_port),
                connect=ConsulConnect(sidecar_service=True),
            )
        ]
        # web group: upstream to api-svc through the injected sidecar
        web_job = mock.job(id="mesh-web")
        web_job.task_groups[0].count = 1
        wt = web_job.task_groups[0].tasks[0]
        wt.driver = "mock_driver"
        wt.config = {"run_for": 60}
        wt.services = [
            Service(
                name="web-svc",
                port_label="9090",
                connect=ConsulConnect(
                    sidecar_service=True,
                    upstreams=[
                        ConnectUpstream(
                            destination_name="api-svc",
                            local_bind_port=bind_port,
                        )
                    ],
                ),
            )
        ]
        server.register_job(api_job)
        server.register_job(web_job)
        assert server.drain_to_idle(15)

        # catalog carries the instance once the api alloc runs
        def alloc_running():
            return any(
                a.client_status == "running"
                for a in server.store.allocs_by_job(
                    "default", "mesh-api"
                )
            )

        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not alloc_running():
            time.sleep(0.1)
        assert alloc_running()
        # the injected proxy task should be live; reach the api
        # through its local bind
        deadline = time.monotonic() + 15
        got = b""
        while time.monotonic() < deadline:
            try:
                c = socket.create_connection(
                    ("127.0.0.1", bind_port), timeout=2
                )
                got = c.recv(1024)
                c.close()
                if got:
                    break
            except OSError:
                time.sleep(0.2)
        assert got == b"api-ok", got
        api_sock.close()
    finally:
        client.stop()
        server.stop()
