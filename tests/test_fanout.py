"""Follower scheduling fan-out (nomad_tpu/server/fanout.py).

Covers the remote broker lease protocol (per-server tracking, batch
dequeue, nack-timeout reclamation of a dead follower's leases, atomic
family drains), the 3-server fan-out vs single-server oracle
placement parity, the replicated generation fence on the remote
submit path, the manager's leadership transitions, and the chaos
smoke with fan-out enabled.
"""
from __future__ import annotations

import threading
import time

from nomad_tpu import mock
from nomad_tpu.raft.chaos import ChaosTransport
from nomad_tpu.server.cluster import TestCluster
from nomad_tpu.server.eval_broker import EvalBroker, job_family
from nomad_tpu.server.fsm import StaleLeadershipError
from nomad_tpu.structs import Evaluation, Plan, new_id

SCHEDS = ["service", "batch", "system", "_core"]


def wait_until(cond, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


def _new_leader(cluster, exclude, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        est = [
            s
            for s in cluster.servers
            if s is not exclude
            and s.is_leader()
            and s._leader_established
        ]
        if est:
            return est[0]
        time.sleep(0.02)
    raise AssertionError("no new leader")


def _nodes(n, prefix="fo-node"):
    return [mock.node(id=f"{prefix}-{i:03d}") for i in range(n)]


def _jobs(n, prefix="fo-job"):
    out = []
    for i in range(n):
        job = mock.job(id=f"{prefix}-{i:04d}")
        job.task_groups[0].count = 1
        for tg in job.task_groups:
            for task in tg.tasks:
                task.resources.cpu = 50
                task.resources.memory_mb = 32
        out.append(job)
    return out


def _live_placements(store):
    out = set()
    for alloc in store.allocs.values():
        if alloc.terminal_status():
            continue
        out.add((alloc.job_id, alloc.task_group, alloc.name))
    return out


def _evals(n, family="fam"):
    return [
        Evaluation(
            id=new_id(),
            namespace="default",
            job_id=f"{family}/dispatch-{i:03d}",
            type="batch",
            priority=50,
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------
# broker-level remote lease protocol
# ---------------------------------------------------------------------


def test_dequeue_remote_tracks_leases_per_server():
    broker = EvalBroker(nack_timeout=60.0)
    broker.set_enabled(True)
    evs = _evals(6)
    broker.enqueue_all(evs)
    a = broker.dequeue_remote(
        ["batch"], timeout=1.0, max_n=3, peer="server-1"
    )
    b = broker.dequeue_remote(
        ["batch"], timeout=1.0, max_n=2, peer="server-2"
    )
    assert len(a) == 3 and len(b) == 2
    # remote leases ARE unacked deliveries: the count and the stats
    # surface both include the RPC-held tokens
    assert broker.unacked_count() == 5
    assert broker.remote_unacked_count() == 5
    assert broker.stats["total_remote_unacked"] == 5
    assert broker.remote_lease_stats() == {
        "server-1": 3, "server-2": 2,
    }
    # ack clears the attribution with the token
    ev, token = a[0]
    broker.ack(ev.id, token)
    assert broker.remote_lease_stats() == {
        "server-1": 2, "server-2": 2,
    }
    assert broker.stats["total_remote_unacked"] == 4
    # nack does too, and the eval goes back to ready
    ev, token = b[0]
    broker.nack(ev.id, token)
    assert broker.remote_lease_stats() == {
        "server-1": 2, "server-2": 1,
    }
    # a flush (leadership revoke) clears every remote lease
    broker.set_enabled(False)
    assert broker.remote_unacked_count() == 0
    assert broker.stats["total_remote_unacked"] == 0


def test_dead_follower_leases_reclaimed_by_sweeper():
    """A follower that dies holding leases must never wedge the
    queue: the nack-timeout sweeper — re-armed from the remote
    dequeue path even if the previous sweeper thread died — nacks
    the leases back to ready for redelivery."""
    broker = EvalBroker(nack_timeout=0.15)
    broker.set_enabled(True)
    # simulate a dead sweeper thread (the PR 12 _ensure_ticker_locked
    # shape): the remote dequeue path must re-arm it on its own
    dead = threading.Thread(target=lambda: None)
    dead.start()
    dead.join()
    with broker._lock:
        broker._ticker = dead
    evs = _evals(4)
    broker.enqueue_all(evs)
    leased = broker.dequeue_remote(
        ["batch"], timeout=1.0, max_n=4, peer="doomed-follower"
    )
    assert len(leased) == 4
    assert broker.remote_unacked_count() == 4
    # the follower dies here: no ack, no nack — only the sweeper
    wait_until(
        lambda: broker.unacked_count() == 0,
        timeout=5.0,
        msg="sweeper reclaim",
    )
    assert broker.remote_unacked_count() == 0
    assert broker.ready_count() == 4  # all redelivered, zero lost
    redelivered = set()
    while True:
        ev, token = broker.dequeue(["batch"], timeout=0.2)
        if ev is None:
            break
        redelivered.add(ev.id)
        broker.ack(ev.id, token)
    assert redelivered == {e.id for e in evs}


def test_drain_family_remote_is_atomic_and_tracked():
    """A family storm drained for a remote server lands WHOLE (the
    contiguous prefix, never leapfrogging an unrelated eval) and is
    attributed to that peer."""
    broker = EvalBroker(nack_timeout=60.0)
    broker.set_enabled(True)
    fam = _evals(5, family="storm")
    other = Evaluation(
        id=new_id(), namespace="default", job_id="unrelated",
        type="batch", priority=50,
    )
    broker.enqueue_all(fam + [other])
    trigger = broker.dequeue_remote(
        ["batch"], timeout=1.0, max_n=1, peer="server-2"
    )
    assert len(trigger) == 1
    drained = broker.drain_family_remote(
        ["batch"], job_family(trigger[0][0]), max_n=16,
        peer="server-2",
    )
    assert [ev.id for ev, _t in drained] == [e.id for e in fam[1:]]
    assert broker.remote_lease_stats() == {"server-2": 5}
    # the unrelated eval was never leapfrogged
    ev, _token = broker.dequeue(["batch"], timeout=0.5)
    assert ev.id == other.id


# ---------------------------------------------------------------------
# cluster-level fan-out
# ---------------------------------------------------------------------


def test_three_server_fanout_matches_single_server_oracle(
    monkeypatch,
):
    """Acceptance: a 3-server fan-out cluster produces a placement
    set identical (order-independent) to the single-server oracle on
    the same workload — and the followers genuinely planned."""
    from nomad_tpu.server import Server

    n_nodes, n_jobs = 6, 24
    # oracle: one plain batch-pipeline server, no fan-out
    oracle = Server(num_schedulers=1, seed=0, batch_pipeline=True)
    oracle.start()
    try:
        for node in _nodes(n_nodes):
            oracle.register_node(node)
        for job in _jobs(n_jobs):
            oracle.register_job(job)
        assert oracle.drain_to_idle(timeout=60.0)
        oracle_placements = _live_placements(oracle.store)
    finally:
        oracle.stop()
    assert len(oracle_placements) == n_jobs

    monkeypatch.setenv("NOMAD_TPU_FANOUT", "1")
    cluster = TestCluster(3, heartbeat_ttl=300.0)
    cluster.start()
    try:
        leader = cluster.wait_for_leader(timeout=30.0)
        for node in _nodes(n_nodes):
            leader.register_node(node)
        for i, job in enumerate(_jobs(n_jobs)):
            cluster.servers[i % 3].register_job(job)
        wait_until(
            lambda: len(
                _live_placements(
                    cluster.wait_for_leader(timeout=30.0).store
                )
            )
            == n_jobs
            and cluster.wait_for_leader(timeout=30.0).drain_to_idle(
                timeout=1.0
            ),
            timeout=90.0,
            msg="fan-out drain",
        )
        leader = cluster.wait_for_leader(timeout=30.0)
        assert _live_placements(leader.store) == oracle_placements
        follower_plans = sum(
            s.metrics.get_counter("fanout.plans_submitted")
            for s in cluster.servers
        )
        assert follower_plans > 0, "fan-out never engaged"
        assert leader.broker.remote_unacked_count() == 0
        assert leader.broker.failed() == []
    finally:
        cluster.stop()


def test_follower_kill_mid_lease_redelivers(monkeypatch):
    """A follower that leased work and died mid-flight loses nothing:
    the leader's sweeper reclaims the leases at the nack timeout and
    the evals are redelivered."""
    cluster = TestCluster(
        3, heartbeat_ttl=300.0, nack_timeout=0.5, num_schedulers=0
    )
    cluster.start()
    try:
        leader = cluster.wait_for_leader(timeout=30.0)
        follower = cluster.followers()[0]
        for node in _nodes(3, prefix="fk-node"):
            leader.register_node(node)
        for job in _jobs(5, prefix="fk-job"):
            leader.register_job(job)
        wait_until(
            lambda: leader.broker.ready_count() == 5,
            msg="evals enqueued",
        )
        # the follower leases over the real RPC surface — then "dies"
        # (never acks, never nacks)
        resp = cluster.transport.rpc(
            follower.addr,
            leader.addr,
            "broker_dequeue",
            {
                "schedulers": SCHEDS,
                "timeout": 1.0,
                "n": 4,
                "server": follower.addr,
            },
        )
        import pickle

        leases = pickle.loads(resp["leases"])
        assert len(leases) == 4
        assert resp["gen"] == leader._leadership_gen
        assert leader.broker.remote_lease_stats() == {
            follower.addr: 4
        }
        wait_until(
            lambda: leader.broker.remote_unacked_count() == 0,
            timeout=10.0,
            msg="lease reclamation",
        )
        # every eval is back in the ready queue — zero lost
        assert leader.broker.ready_count() == 5
    finally:
        cluster.stop()


def test_leader_kill_mid_submit_fenced_on_every_store():
    """A plan leased/produced under a dead leadership and submitted
    through the remote plan path is rejected by the REPLICATED
    generation fence on every store — and a fresh-generation plan on
    the same path commits fine."""
    transport = ChaosTransport(seed=3)
    cluster = TestCluster(
        3, transport=transport, heartbeat_ttl=300.0
    )
    cluster.start()
    try:
        old_leader = cluster.wait_for_leader(timeout=30.0)
        for node in _nodes(3, prefix="lk-node"):
            old_leader.register_node(node)
        old_gen = old_leader._leadership_gen
        # depose the leader with the follower's "plan" in flight
        transport.partition_group([old_leader.addr])
        new_leader = _new_leader(cluster, exclude=old_leader)
        transport.heal(old_leader.addr)
        wait_until(
            lambda: all(
                s.fsm.leadership_fence == new_leader._leadership_gen
                for s in cluster.servers
            ),
            msg="fence replication",
        )
        follower = next(
            s for s in cluster.servers
            if s is not new_leader and s is not old_leader
        )
        node_id = next(iter(new_leader.store.nodes))
        alloc = mock.alloc(node_id=node_id)
        alloc.job = mock.job(id="zombie-fan")
        alloc.job_id = "zombie-fan"
        stale_plan = Plan(
            eval_id="ev-zombie-fan",
            node_allocation={node_id: [alloc]},
            leader_gen=old_gen,  # the dead leadership's lease stamp
        )
        import pickle

        resp = transport.rpc(
            follower.addr,
            new_leader.addr,
            "submit_plan",
            {"plan": pickle.dumps(stale_plan)},
        )
        assert resp.get("stale_leadership"), resp
        gen, fence = resp["stale_leadership"]
        assert gen == old_gen
        assert fence >= new_leader._leadership_gen
        for s in cluster.servers:
            assert s.fsm.store.alloc_by_id(alloc.id) is None, (
                f"zombie alloc committed on {s.addr}"
            )
        # the same path under the CURRENT generation commits
        alloc2 = mock.alloc(node_id=node_id)
        alloc2.job = mock.job(id="fresh-fan")
        alloc2.job_id = "fresh-fan"
        fresh_plan = Plan(
            eval_id="ev-fresh-fan",
            node_allocation={node_id: [alloc2]},
            leader_gen=new_leader._leadership_gen,
        )
        resp = transport.rpc(
            follower.addr,
            new_leader.addr,
            "submit_plan",
            {"plan": pickle.dumps(fresh_plan)},
        )
        assert "result" in resp, resp
        result = pickle.loads(resp["result"])
        assert result.alloc_index > 0
        wait_until(
            lambda: all(
                s.fsm.store.alloc_by_id(alloc2.id) is not None
                for s in cluster.servers
            ),
            msg="fresh plan replication",
        )
    finally:
        transport.disarm()
        cluster.stop()


def test_fanout_workers_follow_leadership(monkeypatch):
    """Fan-out workers run exactly while a server is a follower: a
    follower that takes leadership tears its fleet down, and a
    deposed leader fans out against the new one."""
    monkeypatch.setenv("NOMAD_TPU_FANOUT", "1")
    transport = ChaosTransport(seed=11)
    cluster = TestCluster(
        3, transport=transport, heartbeat_ttl=300.0
    )
    cluster.start()
    try:
        leader = cluster.wait_for_leader(timeout=30.0)
        followers = cluster.followers()
        wait_until(
            lambda: all(f.fanout.active() for f in followers),
            msg="followers fanned out",
        )
        assert not leader.fanout.active()
        # depose: one follower takes over and must stop its fleet
        transport.partition_group([leader.addr])
        new_leader = _new_leader(cluster, exclude=leader)
        transport.heal(leader.addr)
        wait_until(
            lambda: not new_leader.fanout.active(),
            msg="new leader tore fan-out down",
        )
        # the deposed leader re-joins as a follower and fans out
        wait_until(
            lambda: leader.fanout.active(),
            timeout=30.0,
            msg="old leader fanned out as follower",
        )
    finally:
        transport.disarm()
        cluster.stop()


def test_chaos_smoke_with_fanout_small():
    """The leadership-loss chaos smoke at test scale WITH followers
    planning: kills exercise remote-lease death and the replicated
    fence on follower plans — zero lost, zero duplicates vs the
    oracle, and the fan-out genuinely engaged."""
    from nomad_tpu.raft.chaos_smoke import run_smoke

    block = run_smoke(jobs=40, kills=1, nodes=4, fanout=True)
    assert block["ok"], block
    assert block["fanout"] and block["fanout_engaged"]
    assert block["oracle_match"]
    assert block["lost_evals"] == 0
    assert block["duplicate_placements"] == 0
    assert block["counters"]["fanout.plans_submitted"] > 0


# ---------------------------------------------------------------------
# mirror lifecycle on lease handback (park vs dispose)
# ---------------------------------------------------------------------


def test_stop_workers_parks_fleet_and_marks_mirrors_dirty(monkeypatch):
    """A leadership-change teardown PARKS the fan-out workers — same
    objects, device mirrors marked dirty — so re-establishment catches
    up in O(dirty rows) deltas instead of a full-world resync; only
    manager shutdown disposes the fleet."""
    monkeypatch.setenv("NOMAD_TPU_FANOUT", "1")
    monkeypatch.setenv("NOMAD_TPU_FANOUT_WORKERS", "1")
    cluster = TestCluster(3, heartbeat_ttl=300.0)
    cluster.start()
    try:
        cluster.wait_for_leader(timeout=30.0)
        followers = cluster.followers()
        wait_until(
            lambda: all(f.fanout.active() for f in followers),
            msg="followers fanned out",
        )
        mgr = followers[0].fanout
        workers = list(mgr.workers)
        assert workers, "no fan-out workers established"
        assert all(
            getattr(w, "_is_fanout_worker", False) for w in workers
        )
        # quiesce the monitor so the park below isn't instantly undone
        # (its exit path runs the same park teardown)
        mgr._stop.set()
        mgr._thread.join(timeout=10.0)
        mgr._thread = None
        # a fresh worker starts dirty; clear so the assert is real
        for w in workers:
            w._mirror_dirty = False
            w._mirror_dirty_sharded = False
        mgr._stop_workers()
        assert not mgr.active()
        assert mgr.workers == workers, "park discarded the fleet"
        for w in workers:
            assert w._mirror_dirty and w._mirror_dirty_sharded, (
                "parked worker's mirrors not marked dirty — the "
                "catch-up sync would donate buffers an abandoned "
                "launch may still be reading"
            )
        # re-establishment reuses the SAME parked workers
        mgr._ensure_workers()
        wait_until(lambda: mgr.active(), msg="fleet re-established")
        assert mgr.workers == workers
        # manager shutdown is the dispose path: fleet released
        mgr.stop()
        assert mgr.workers == []
        assert not mgr.active()
    finally:
        cluster.stop()


def test_fanout_mesh_knob_reserves_mesh_for_fanout_workers(monkeypatch):
    """With NOMAD_TPU_FANOUT_MESH=1 only the marked fan-out worker may
    bring the device mesh up — a process hosting both a leader's main
    workers and a follower fan-out worker must not have two workers
    racing for one jax.distributed world / pod head port."""
    from types import SimpleNamespace

    from nomad_tpu.server.batch_worker import BatchWorker

    monkeypatch.delenv("NOMAD_TPU_FANOUT_MESH", raising=False)
    plain = SimpleNamespace(_is_fanout_worker=False)
    marked = SimpleNamespace(_is_fanout_worker=True)
    assert BatchWorker._mesh_allowed(plain)
    assert BatchWorker._mesh_allowed(marked)
    monkeypatch.setenv("NOMAD_TPU_FANOUT_MESH", "1")
    assert not BatchWorker._mesh_allowed(plain)
    assert BatchWorker._mesh_allowed(marked)
