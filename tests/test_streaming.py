"""Streaming transports: interactive `alloc exec` over websocket,
`alloc logs -f` over chunked HTTP, `agent monitor` live stream
(reference nomad/rpc.go handleStreamingConn + command/alloc_exec.go;
VERDICT r3 missing #2)."""
import base64
import json
import os
import tempfile
import time
import urllib.parse
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.api import start_http_server
from nomad_tpu.api.ws import WebSocketClient
from nomad_tpu.client.client import Client
from nomad_tpu.server import Server
from nomad_tpu.structs import Resources, Task


@pytest.fixture
def live_task_cluster():
    os.environ.setdefault("NOMAD_TPU_EXEC_ISOLATION", "0")
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=7)
    server.start()
    base_dir = tempfile.mkdtemp()
    client = Client(
        server, node=mock.node(), fingerprint=False,
        data_dir=base_dir,
    )
    client.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"

    job = mock.job(id="stream-job")
    tg = job.task_groups[0]
    tg.count = 1
    tg.tasks = [
        Task(
            name="main",
            driver="raw_exec",
            config={
                "command": "/bin/sh",
                "args": [
                    "-c",
                    "i=0; while [ $i -lt 600 ]; do "
                    "echo line-$i; i=$((i+1)); sleep 0.2; done",
                ],
            },
            resources=Resources(cpu=100, memory_mb=64),
        )
    ]
    server.register_job(job)
    alloc = None
    deadline = time.time() + 30
    while time.time() < deadline:
        allocs = server.store.allocs_by_job("default", "stream-job")
        if allocs and allocs[0].client_status == "running":
            alloc = allocs[0]
            break
        time.sleep(0.25)
    assert alloc is not None, "task never started"
    yield server, client, base, alloc
    http.stop()
    client.stop()
    server.stop()


def test_interactive_exec_websocket(live_task_cluster):
    """A live bidirectional session: stdin frames reach the command,
    stdout frames stream back, the exit code propagates."""
    _server, _client, base, alloc = live_task_cluster
    host, port = base.replace("http://", "").split(":")
    cmd = json.dumps(["/bin/sh", "-c", "read x; echo got-$x; exit 3"])
    ws = WebSocketClient(
        host,
        int(port),
        f"/v1/client/allocation/{alloc.id}/exec"
        f"?task=main&command={urllib.parse.quote(cmd)}",
    )
    try:
        ws.send_text(
            json.dumps(
                {
                    "stdin": {
                        "data": base64.b64encode(
                            b"hello\n"
                        ).decode()
                    }
                }
            )
        )
        out = b""
        code = None
        deadline = time.time() + 20
        while time.time() < deadline:
            got = ws.recv(timeout=5)
            if got is None:
                break
            _op, payload = got
            msg = json.loads(payload.decode())
            frame = msg.get("stdout") or {}
            if frame.get("data"):
                out += base64.b64decode(frame["data"])
            if msg.get("exited"):
                code = msg["result"]["exit_code"]
                break
        assert out.strip() == b"got-hello", out
        assert code == 3
    finally:
        ws.close()


def test_alloc_logs_follow_streams_appended_lines(live_task_cluster):
    """logs -f: the chunked stream delivers lines appended AFTER the
    stream opened (true following, not snapshot polling)."""
    _server, _client, base, alloc = live_task_cluster
    url = (
        f"{base}/v1/client/fs/logs/{alloc.id}"
        "?task=main&type=stdout&follow=true"
    )
    resp = urllib.request.urlopen(url, timeout=30)
    assert resp.headers.get("X-Nomad-Stream") == "chunked"
    got = b""
    deadline = time.time() + 30
    first_len = None
    while time.time() < deadline:
        data = resp.read1(65536)
        if not data:
            break
        got += data
        if first_len is None:
            first_len = len(got)
        # saw at least 3 lines beyond the initial burst: following
        if got.count(b"\n") >= (got[:first_len].count(b"\n") + 3):
            break
    resp.close()
    lines = got.decode().strip().splitlines()
    assert len(lines) >= 3, lines
    assert all(line.startswith("line-") for line in lines), lines
    # monotonically increasing line numbers — streamed in order
    nums = [int(line.split("-")[1]) for line in lines]
    assert nums == sorted(nums)


def test_agent_monitor_follow_streams(live_task_cluster):
    """agent monitor -f: live JSON-line stream of agent log records."""
    server, _client, base, _alloc = live_task_cluster
    url = f"{base}/v1/agent/monitor?follow=true"
    resp = urllib.request.urlopen(url, timeout=30)
    server.log_monitor.write_line("stream-marker-1")
    server.log_monitor.write_line("stream-marker-2")
    got = b""
    deadline = time.time() + 15
    while time.time() < deadline and b"stream-marker-2" not in got:
        data = resp.read1(65536)
        if not data:
            break
        got += data
    resp.close()
    lines = [
        json.loads(line)["Line"]
        for line in got.decode().strip().splitlines()
        if line
    ]
    assert any("stream-marker-1" in ln for ln in lines), lines
    assert any("stream-marker-2" in ln for ln in lines), lines


def test_logs_follow_unknown_alloc_404s(live_task_cluster):
    """follow=true must 404 BEFORE the chunked headers for an unknown
    alloc — not stream clean emptiness (code-review r4)."""
    _server, _client, base, _alloc = live_task_cluster
    url = (
        f"{base}/v1/client/fs/logs/no-such-alloc"
        "?task=main&type=stdout&follow=true"
    )
    with pytest.raises(urllib.request.HTTPError) as exc:
        urllib.request.urlopen(url, timeout=10)
    assert exc.value.code == 404


def test_follow_task_log_bounded_steps_and_rotation(tmp_path):
    """follow_task_log caps bytes per step (the cursor resumes where
    the step stopped) and crosses rotations without duplicating or
    reordering data."""
    from nomad_tpu.client.logmon import follow_task_log

    log_dir = str(tmp_path)
    # two rotated files, 300KB total
    with open(tmp_path / "main.stdout.0", "wb") as f:
        f.write(b"a" * 200_000)
    with open(tmp_path / "main.stdout.1", "wb") as f:
        f.write(b"b" * 100_000)
    got = b""
    cursor = None
    for _ in range(10):
        data, cursor = follow_task_log(
            log_dir, "main", "stdout", cursor,
            max_step_bytes=64 * 1024,
        )
        if not data:
            break
        assert len(data) <= 64 * 1024
        got += data
    assert got == b"a" * 200_000 + b"b" * 100_000
    # appended data after the cursor caught up
    with open(tmp_path / "main.stdout.1", "ab") as f:
        f.write(b"c" * 10)
    data, cursor = follow_task_log(
        log_dir, "main", "stdout", cursor
    )
    assert data == b"c" * 10
    # a pruned cursor file (all retained files strictly newer) must
    # not re-deliver: simulate by rotating far ahead
    (tmp_path / "main.stdout.0").unlink()
    (tmp_path / "main.stdout.1").unlink()
    with open(tmp_path / "main.stdout.5", "wb") as f:
        f.write(b"fresh")
    data, cursor = follow_task_log(
        log_dir, "main", "stdout", cursor
    )
    assert data == b"fresh"


def test_follow_task_log_rotation_restart_no_duplicates(tmp_path):
    """When the retained rotation indexes RESTART below an established
    cursor (task restart recreated index 0 after GC), the follower
    resumes at the newest file's end instead of replaying from the
    oldest retained file — the consumer must never see bytes twice
    (ADVICE r4)."""
    from nomad_tpu.client.logmon import follow_task_log

    log_dir = str(tmp_path)
    with open(tmp_path / "main.stdout.5", "wb") as f:
        f.write(b"old-generation")
    data, cursor = follow_task_log(log_dir, "main", "stdout", None)
    assert data == b"old-generation"
    assert cursor[0] == 5
    # restart: old files GCed, a fresh index 0 appears with content
    # the follower can't distinguish from already-streamed bytes
    (tmp_path / "main.stdout.5").unlink()
    # transient window where rotation files AND flat file are both
    # gone: the cursor must hold position, not degrade to (-1, 0)
    data, held = follow_task_log(log_dir, "main", "stdout", cursor)
    assert data == b"" and held == cursor
    with open(tmp_path / "main.stdout.0", "wb") as f:
        f.write(b"maybe-already-seen")
    data, cursor = follow_task_log(log_dir, "main", "stdout", cursor)
    assert data == b""  # no replay
    assert cursor == (0, len(b"maybe-already-seen"))
    # bytes appended AFTER the resync do stream
    with open(tmp_path / "main.stdout.0", "ab") as f:
        f.write(b"+new")
    data, cursor = follow_task_log(log_dir, "main", "stdout", cursor)
    assert data == b"+new"

    # rotation files vanishing entirely mid-follow (flat fallback):
    # an established rotation cursor resumes at the flat file's end
    (tmp_path / "main.stdout.0").unlink()
    flat = tmp_path / "main.stdout"
    flat.write_bytes(b"flat-history")
    data, cursor = follow_task_log(
        log_dir, "main", "stdout", cursor, flat_path=str(flat)
    )
    assert data == b""
    assert cursor == (-1, len(b"flat-history"))
    with flat.open("ab") as f:
        f.write(b"!tail")
    data, cursor = follow_task_log(
        log_dir, "main", "stdout", cursor, flat_path=str(flat)
    )
    assert data == b"!tail"


def test_logs_follow_disconnect_frees_server_thread(
    live_task_cluster,
):
    """A consumer hanging up mid-stream must not pin the serving
    thread: the chunked writer detects the closed socket on its next
    idle tick and returns (VERDICT r4 weak #7)."""
    import http.client as _http
    import threading

    _server, _client, base, alloc = live_task_cluster
    host, port = base.replace("http://", "").split(":")

    before = threading.active_count()
    conns = []
    for _ in range(3):
        conn = _http.HTTPConnection(host, int(port), timeout=10)
        conn.request(
            "GET",
            f"/v1/client/fs/logs/{alloc.id}"
            "?task=main&type=stdout&follow=true",
        )
        resp = conn.getresponse()
        assert resp.status == 200
        # read one chunk so the stream is established, then hang up
        assert resp.read1(4096)
        conns.append(conn)
    for conn in conns:
        conn.close()
    # server threads drain once their next write/idle-tick notices
    deadline = time.time() + 15
    while time.time() < deadline:
        if threading.active_count() <= before:
            break
        time.sleep(0.25)
    assert threading.active_count() <= before + 1, (
        threading.active_count(), before,
    )


def test_concurrent_followers_see_the_same_stream(
    live_task_cluster,
):
    """Several logs -f consumers on ONE alloc: each gets the appended
    lines independently (per-consumer cursors, no interleaving
    corruption)."""
    import http.client as _http

    _server, _client, base, alloc = live_task_cluster
    host, port = base.replace("http://", "").split(":")

    readers = []
    for _ in range(3):
        conn = _http.HTTPConnection(host, int(port), timeout=20)
        conn.request(
            "GET",
            f"/v1/client/fs/logs/{alloc.id}"
            "?task=main&type=stdout&follow=true",
        )
        resp = conn.getresponse()
        assert resp.status == 200
        readers.append((conn, resp))
    got = [b"" for _ in readers]
    deadline = time.time() + 20
    while time.time() < deadline and not all(
        b"line-" in g and g.count(b"\n") >= 2 for g in got
    ):
        for i, (_conn, resp) in enumerate(readers):
            resp.fp.raw._sock.settimeout(1.0)
            try:
                got[i] += resp.read1(4096)
            except Exception:  # noqa: BLE001
                continue
    for conn, _resp in readers:
        conn.close()
    for g in got:
        assert b"line-" in g, got
        # frames carry whole lines in order: the first two observed
        # indices must be consecutive
        lines = [
            int(x.split(b"-")[1])
            for x in g.split() if x.startswith(b"line-")
        ]
        assert lines == sorted(lines), lines
