"""Operator snapshot + agent config tests (reference model:
helper/snapshot tests, command/agent/config_parse_test.go).
"""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.config import load_config
from nomad_tpu.server import Server
from nomad_tpu.server.snapshot import restore_snapshot, save_snapshot


def test_snapshot_roundtrip(tmp_path):
    src = Server(num_schedulers=1, seed=77)
    src.start()
    try:
        for _ in range(3):
            src.register_node(mock.node())
        job = mock.job(id="snapjob")
        job.task_groups[0].count = 3
        src.register_job(job)
        assert src.drain_to_idle(10)
        src.acls.enabled = True
        token = src.acls.bootstrap()
        path = str(tmp_path / "state.snap")
        save_snapshot(src, path)
    finally:
        src.stop()

    dst = Server(num_schedulers=1, seed=77)
    index = restore_snapshot(dst, path)
    assert index > 0
    dst.start()
    try:
        assert len(list(dst.store.iter_nodes())) == 3
        assert dst.store.job_by_id("default", "snapjob") is not None
        allocs = dst.store.allocs_by_job("default", "snapjob")
        assert len(allocs) == 3
        # node table usage rebuilt
        row = dst.store.node_table.row_of[allocs[0].node_id]
        assert dst.store.node_table.cpu_used[row] > 0
        # ACLs restored
        assert dst.acls.enabled
        assert dst.acls.resolve(token.secret_id).management
        # the restored control plane still schedules
        job2 = mock.job(id="post-restore")
        job2.task_groups[0].count = 1
        dst.register_job(job2)
        assert dst.drain_to_idle(10)
        assert dst.store.allocs_by_job("default", "post-restore")
    finally:
        dst.stop()


def test_snapshot_restores_pending_evals(tmp_path):
    src = Server(num_schedulers=0, seed=1)  # no workers: evals stay pending
    src.start()
    try:
        src.register_node(mock.node())
        job = mock.job(id="pending")
        src.register_job(job)
        path = str(tmp_path / "state.snap")
        save_snapshot(src, path)
    finally:
        src.stop()

    dst = Server(num_schedulers=1, seed=1)
    restore_snapshot(dst, path)
    dst.start()  # restore_evals re-enqueues the pending eval
    try:
        assert dst.drain_to_idle(10)
        assert dst.store.allocs_by_job("default", "pending")
    finally:
        dst.stop()


HCL_CONFIG = """
data_dir   = "/tmp/nomad-tpu-test"
datacenter = "dc7"

server {
  enabled        = true
  num_schedulers = 4
  batch_pipeline = true
  heartbeat_ttl  = "45s"
}

client {
  enabled = true
  drivers = ["mock_driver"]
}

http {
  port = 5646
}

acl { enabled = true }
"""


def test_load_hcl_config(tmp_path):
    p = tmp_path / "agent.hcl"
    p.write_text(HCL_CONFIG)
    cfg = load_config(str(p))
    assert cfg.data_dir == "/tmp/nomad-tpu-test"
    assert cfg.datacenter == "dc7"
    assert cfg.server.num_schedulers == 4
    assert cfg.server.batch_pipeline is True
    assert cfg.server.heartbeat_ttl_s == 45.0
    assert cfg.client.enabled is True
    assert cfg.client.drivers == ["mock_driver"]
    assert cfg.http.port == 5646
    assert cfg.acl.enabled is True


def test_load_json_config(tmp_path):
    p = tmp_path / "agent.json"
    p.write_text(
        '{"server": {"num_schedulers": 8}, "http": {"port": 7000}}'
    )
    cfg = load_config(str(p))
    assert cfg.server.num_schedulers == 8
    assert cfg.http.port == 7000
    assert cfg.client.enabled is False


def test_snapshot_restore_rebuilds_port_and_device_indexes(tmp_path):
    """install_payload must clear + rebuild the derived static-port
    occupancy indexes (_ports_live/_ports_by_node) and the node
    table's device_used: phantom pre-restore entries would skew the
    batch kernel's port_used0 columns and silently change winners vs
    the serial walk (ADVICE r4 medium)."""
    from nomad_tpu.structs import NetworkResource, Port

    def static_job(jid):
        job = mock.job(id=jid)
        job.task_groups[0].count = 1
        job.task_groups[0].networks = [
            NetworkResource(reserved_ports=[Port("svc", 8080)])
        ]
        return job

    src = Server(num_schedulers=1, seed=3)
    src.start()
    try:
        src.register_node(mock.node())
        src.register_job(static_job("portjob"))
        assert src.drain_to_idle(10)
        assert src.store._ports_live.get(8080)
        path = str(tmp_path / "state.snap")
        save_snapshot(src, path)
    finally:
        src.stop()

    # dst carries PRE-restore state holding a DIFFERENT static port:
    # a phantom that must not survive the restore
    dst = Server(num_schedulers=1, seed=3)
    dst.start()
    try:
        dst.register_node(mock.node())
        dst.register_job(static_job("phantom"))
        assert dst.drain_to_idle(10)
        phantom_nodes = set(dst.store._ports_live.get(8080, ()))
        assert phantom_nodes
        restore_snapshot(dst, path)
        live = dst.store._ports_live.get(8080, {})
        # the snapshot's occupancy is present...
        assert live
        # ...and the pre-restore phantom node is gone
        assert not (set(live) & phantom_nodes)
        # _ports_by_node only references restored nodes
        assert set(dst.store._ports_by_node) <= set(dst.store.nodes)
    finally:
        dst.stop()
