"""Control-plane integration tests: broker, blocked evals, plan applier,
workers, heartbeats (reference model: nomad/eval_broker_test.go,
blocked_evals_test.go, plan_apply_test.go, worker_test.go).
"""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import EvalBroker, Server
from nomad_tpu.server.plan_apply import evaluate_plan
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_RUNNING,
    Allocation,
    AllocatedResources,
    AllocatedTaskResources,
    Evaluation,
    NODE_STATUS_DOWN,
    Plan,
)


# ---------------------------------------------------------------------------
# broker
# ---------------------------------------------------------------------------


def test_broker_priority_order():
    b = EvalBroker()
    b.set_enabled(True)
    low = mock.evaluation(priority=10, job_id="a")
    high = mock.evaluation(priority=90, job_id="b")
    b.enqueue(low)
    b.enqueue(high)
    ev, token = b.dequeue(["service"], timeout=1)
    assert ev is high
    b.ack(ev.id, token)
    ev2, token2 = b.dequeue(["service"], timeout=1)
    assert ev2 is low
    b.ack(ev2.id, token2)


def test_broker_job_dedup():
    """Two evals for one job: the second waits until the first acks
    (reference structs.go:9535)."""
    b = EvalBroker()
    b.set_enabled(True)
    e1 = mock.evaluation(job_id="job1")
    e2 = mock.evaluation(job_id="job1")
    b.enqueue(e1)
    b.enqueue(e2)
    ev, token = b.dequeue(["service"], timeout=1)
    assert ev is e1
    # second eval for same job is not available yet
    ev_none, _ = b.dequeue(["service"], timeout=0.1)
    assert ev_none is None
    b.ack(e1.id, token)
    ev2, token2 = b.dequeue(["service"], timeout=1)
    assert ev2 is e2
    b.ack(e2.id, token2)


def test_broker_nack_redelivery_and_failed_queue():
    b = EvalBroker(delivery_limit=2)
    b.set_enabled(True)
    e = mock.evaluation(job_id="j")
    b.enqueue(e)
    ev, token = b.dequeue(["service"], timeout=1)
    b.nack(ev.id, token)
    ev, token = b.dequeue(["service"], timeout=1)
    assert ev is e
    b.nack(ev.id, token)
    # hit the delivery limit -> failed queue
    assert b.failed() == [e]
    ev_none, _ = b.dequeue(["service"], timeout=0.1)
    assert ev_none is None


def test_broker_nack_timeout_redelivers():
    b = EvalBroker(nack_timeout=0.1)
    b.set_enabled(True)
    e = mock.evaluation(job_id="j")
    b.enqueue(e)
    ev, token = b.dequeue(["service"], timeout=1)
    # never ack; timer should nack for us
    ev2, token2 = b.dequeue(["service"], timeout=2)
    assert ev2 is e
    b.ack(ev2.id, token2)


def test_broker_token_mismatch():
    b = EvalBroker()
    b.set_enabled(True)
    e = mock.evaluation(job_id="j")
    b.enqueue(e)
    ev, token = b.dequeue(["service"], timeout=1)
    with pytest.raises(ValueError):
        b.ack(ev.id, "bogus")
    b.ack(ev.id, token)


def test_broker_delayed_eval():
    b = EvalBroker()
    b.set_enabled(True)
    e = mock.evaluation(job_id="j")
    e.wait_until = time.time() + 0.2
    b.enqueue(e)
    ev, _ = b.dequeue(["service"], timeout=0.05)
    assert ev is None
    ev, token = b.dequeue(["service"], timeout=2)
    assert ev is e
    b.ack(ev.id, token)


# ---------------------------------------------------------------------------
# plan applier verification
# ---------------------------------------------------------------------------


def _resources(cpu, mem):
    return AllocatedResources(
        tasks={"t": AllocatedTaskResources(cpu=cpu, memory_mb=mem)}
    )


def test_evaluate_plan_partial_commit():
    store = StateStore()
    n1 = mock.node()
    n2 = mock.node()
    store.upsert_node(n1)
    store.upsert_node(n2)
    # fill n2 completely
    filler = mock.alloc(node_id=n2.id)
    filler.allocated_resources = _resources(3900, 7900)
    store.upsert_allocs([filler])

    plan = Plan(
        node_allocation={
            n1.id: [
                mock.alloc(node_id=n1.id)
            ],
            n2.id: [
                mock.alloc(node_id=n2.id)
            ],
        }
    )
    result, full = evaluate_plan(store, plan)
    assert not full
    assert n1.id in result.node_allocation
    assert n2.id not in result.node_allocation
    assert result.refresh_index > 0


def test_evaluate_plan_all_at_once_rejects_everything():
    store = StateStore()
    n1 = mock.node()
    n2 = mock.node()
    store.upsert_node(n1)
    store.upsert_node(n2)
    filler = mock.alloc(node_id=n2.id)
    filler.allocated_resources = _resources(3900, 7900)
    store.upsert_allocs([filler])
    plan = Plan(
        all_at_once=True,
        node_allocation={
            n1.id: [mock.alloc(node_id=n1.id)],
            n2.id: [mock.alloc(node_id=n2.id)],
        },
    )
    result, full = evaluate_plan(store, plan)
    assert not full
    assert not result.node_allocation


def test_evaluate_plan_stops_always_fit():
    store = StateStore()
    n = mock.node()
    store.upsert_node(n)
    a = mock.alloc(node_id=n.id)
    store.upsert_allocs([a])
    plan = Plan(node_update={n.id: [a]})
    result, full = evaluate_plan(store, plan)
    assert full


# ---------------------------------------------------------------------------
# plan applier pipelining (reference plan_apply.go:45-70 + EvaluatePool)
# ---------------------------------------------------------------------------


class _SlowStore:
    """Store facade with injected apply/read latency, standing in for a
    raft-replicated store (server/cluster.py) whose plan commits pay a
    replication round trip."""

    def __init__(self, store, apply_latency=0.0, read_latency=0.0,
                 fail_applies=0):
        self._store = store
        self.apply_latency = apply_latency
        self.read_latency = read_latency
        self.fail_applies = fail_applies
        self.applies = 0

    def __getattr__(self, name):
        return getattr(self._store, name)

    def allocs_by_node(self, node_id):
        if self.read_latency:
            time.sleep(self.read_latency)
        return self._store.allocs_by_node(node_id)

    def upsert_plan_results(self, result, eval_id=""):
        if self.apply_latency:
            time.sleep(self.apply_latency)
        self.applies += 1
        if self.applies <= self.fail_applies:
            raise RuntimeError("injected apply failure")
        return self._store.upsert_plan_results(result, eval_id)


def _pipelined_applier(slow):
    from nomad_tpu.server.plan_apply import PlanApplier
    from nomad_tpu.server.plan_queue import PlanQueue

    pq = PlanQueue()
    pq.set_enabled(True)
    applier = PlanApplier(slow, pq)
    applier.start()
    return pq, applier


def test_plan_apply_pipelines_verification_with_apply_latency():
    """With apply latency L and verify latency V, the pipelined applier
    overlaps plan N+1's verification with plan N's apply: total wall
    time approaches V + K*L instead of the serial K*(V+L)."""
    store = StateStore()
    nodes = [mock.node() for _ in range(4)]
    for n in nodes:
        store.upsert_node(n)
    V, L, K = 0.15, 0.25, 4
    slow = _SlowStore(store, apply_latency=L, read_latency=V)
    pq, applier = _pipelined_applier(slow)
    try:
        pendings = [
            pq.enqueue(
                Plan(node_allocation={n.id: [mock.alloc(node_id=n.id)]})
            )
            for n in nodes
        ]
        t0 = time.monotonic()
        results = [p.wait(timeout=10) for p in pendings]
        elapsed = time.monotonic() - t0
    finally:
        applier.stop()
    assert all(r.node_allocation for r in results)
    # verification of later plans ran while earlier applies were in
    # flight (the overlay path)
    assert applier.overlap_verifies >= 2
    # serial floor is K*(V+L) = 1.6s; pipelined ~ V + K*L = 1.15s
    assert elapsed < K * (V + L) - 0.2, elapsed


def test_plan_apply_overlap_sees_inflight_placements():
    """Optimistic verification must count verified-but-uncommitted
    placements: two plans racing for one slot commit exactly one."""
    store = StateStore()
    n = mock.node()
    store.upsert_node(n)
    big1, big2 = mock.alloc(node_id=n.id), mock.alloc(node_id=n.id)
    big1.allocated_resources = _resources(3000, 6000)
    big2.allocated_resources = _resources(3000, 6000)
    slow = _SlowStore(store, apply_latency=0.2)
    pq, applier = _pipelined_applier(slow)
    try:
        p1 = pq.enqueue(Plan(node_allocation={n.id: [big1]}))
        p2 = pq.enqueue(Plan(node_allocation={n.id: [big2]}))
        r1 = p1.wait(timeout=5)
        r2 = p2.wait(timeout=5)
    finally:
        applier.stop()
    assert r1.node_allocation
    assert not r2.node_allocation
    assert r2.refresh_index > 0
    live = [
        a for a in store.allocs_by_node(n.id) if not a.terminal_status()
    ]
    assert len(live) == 1


def test_plan_apply_failure_invalidates_optimistic_verifications():
    """If plan N's apply fails after plan N+1 was verified against its
    overlay, N+1 re-verifies on real state before committing — the slot
    N would have taken is genuinely free again."""
    store = StateStore()
    n = mock.node()
    store.upsert_node(n)
    big1, big2 = mock.alloc(node_id=n.id), mock.alloc(node_id=n.id)
    big1.allocated_resources = _resources(3000, 6000)
    big2.allocated_resources = _resources(3000, 6000)
    slow = _SlowStore(store, apply_latency=0.2, fail_applies=1)
    pq, applier = _pipelined_applier(slow)
    try:
        p1 = pq.enqueue(Plan(node_allocation={n.id: [big1]}))
        p2 = pq.enqueue(Plan(node_allocation={n.id: [big2]}))
        with pytest.raises(RuntimeError):
            p1.wait(timeout=5)
        r2 = p2.wait(timeout=5)
    finally:
        applier.stop()
    assert r2.node_allocation, "plan 2 must win the freed slot"
    live = [
        a for a in store.allocs_by_node(n.id) if not a.terminal_status()
    ]
    assert [a.id for a in live] == [big2.id]


def test_evaluate_pool_matches_serial():
    from nomad_tpu.server.plan_apply import EvaluatePool

    store = StateStore()
    nodes = [mock.node() for _ in range(10)]
    for n in nodes:
        store.upsert_node(n)
    # fill half the nodes so the pool must reject those placements
    for n in nodes[::2]:
        filler = mock.alloc(node_id=n.id)
        filler.allocated_resources = _resources(3900, 7900)
        store.upsert_allocs([filler])
    plan = Plan(
        node_allocation={
            n.id: [mock.alloc(node_id=n.id)] for n in nodes
        }
    )
    pool = EvaluatePool(workers=4)
    serial, full_s = evaluate_plan(store, plan)
    pooled, full_p = evaluate_plan(store, plan, pool)
    pool.shutdown()
    assert full_s == full_p
    assert set(serial.node_allocation) == set(pooled.node_allocation)
    assert serial.node_allocation == pooled.node_allocation


# ---------------------------------------------------------------------------
# full server loop
# ---------------------------------------------------------------------------


@pytest.fixture
def server():
    s = Server(num_schedulers=2, heartbeat_ttl=60.0, seed=42)
    s.start()
    yield s
    s.stop()


def test_server_end_to_end_placement(server):
    for _ in range(5):
        server.register_node(mock.node())
    job = mock.job()
    job.task_groups[0].count = 5
    server.register_job(job)
    assert server.drain_to_idle(10)
    allocs = server.store.allocs_by_job(job.namespace, job.id)
    assert len(allocs) == 5
    ev = server.store.evals_by_job(job.namespace, job.id)[0]
    assert ev.status == "complete"


def test_server_blocked_eval_unblocks_on_capacity(server):
    # tiny node, job too large => blocked
    n = mock.node()
    n.node_resources.cpu = 600
    n.node_resources.memory_mb = 512
    from nomad_tpu.structs import compute_node_class

    n.computed_class = compute_node_class(n)
    server.register_node(n)
    job = mock.job()
    job.task_groups[0].count = 2
    job.task_groups[0].tasks[0].resources.cpu = 400
    job.task_groups[0].tasks[0].resources.memory_mb = 256
    server.register_job(job)
    assert server.drain_to_idle(10)
    placed = [
        a
        for a in server.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status()
    ]
    assert len(placed) < 2
    assert server.blocked.blocked_count() >= 1
    # add capacity: blocked eval re-runs and completes the job.
    # unblock -> enqueue -> schedule is asynchronous; poll rather than
    # racing a single fixed sleep against a loaded machine
    big = mock.node()
    server.register_node(big)

    def fully_placed():
        server.drain_to_idle(10)
        return (
            len(
                [
                    a
                    for a in server.store.allocs_by_job(
                        job.namespace, job.id
                    )
                    if not a.terminal_status()
                ]
            )
            == 2
        )

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not fully_placed():
        time.sleep(0.1)
    assert fully_placed()


def test_server_node_down_reschedules(server):
    nodes = [mock.node() for _ in range(3)]
    for n in nodes:
        server.register_node(n)
    job = mock.job()
    job.task_groups[0].count = 3
    server.register_job(job)
    assert server.drain_to_idle(10)
    allocs = server.store.allocs_by_job(job.namespace, job.id)
    victim_node = allocs[0].node_id
    # mark allocs running so loss is observable
    for a in allocs:
        a.client_status = ALLOC_CLIENT_STATUS_RUNNING
    server.store.upsert_allocs(allocs)

    server.update_node_status(victim_node, NODE_STATUS_DOWN)
    assert server.drain_to_idle(10)
    time.sleep(0.2)
    server.drain_to_idle(10)
    live = [
        a
        for a in server.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status()
    ]
    assert len(live) == 3
    assert all(a.node_id != victim_node for a in live)
    lost = [
        a
        for a in server.store.allocs_by_job(job.namespace, job.id)
        if a.client_status == "lost"
    ]
    assert lost


def test_server_job_deregister_stops_allocs(server):
    for _ in range(3):
        server.register_node(mock.node())
    job = mock.job()
    job.task_groups[0].count = 3
    server.register_job(job)
    assert server.drain_to_idle(10)
    server.deregister_job(job.namespace, job.id)
    assert server.drain_to_idle(10)
    live = [
        a
        for a in server.store.allocs_by_job(job.namespace, job.id)
        if a.desired_status == "run"
    ]
    assert not live


def test_server_job_validation(server):
    bad = mock.job()
    bad.task_groups = []
    with pytest.raises(ValueError):
        server.register_job(bad)
    bad2 = mock.job()
    bad2.type = "bogus"
    with pytest.raises(ValueError):
        server.register_job(bad2)


def test_server_system_job_runs_everywhere(server):
    nodes = [mock.node() for _ in range(4)]
    for n in nodes:
        server.register_node(n)
    job = mock.system_job()
    server.register_job(job)
    assert server.drain_to_idle(10)
    allocs = server.store.allocs_by_job(job.namespace, job.id)
    assert len(allocs) == 4
    assert {a.node_id for a in allocs} == {n.id for n in nodes}
    # a new node joining gets the system job too
    late = mock.node()
    server.register_node(late)
    assert server.drain_to_idle(10)
    time.sleep(0.2)
    server.drain_to_idle(10)
    allocs = server.store.allocs_by_job(job.namespace, job.id)
    assert late.id in {a.node_id for a in allocs}


def test_server_heartbeat_expiry():
    s = Server(num_schedulers=1, heartbeat_ttl=0.2, seed=1)
    s.start()
    try:
        n = mock.node()
        s.register_node(n)
        time.sleep(0.5)
        assert s.store.node_by_id(n.id).status == NODE_STATUS_DOWN
        # heartbeat revives
        s.heartbeat(n.id)
        assert s.store.node_by_id(n.id).status == "ready"
    finally:
        s.stop()
