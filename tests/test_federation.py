"""Multi-region federation tests: the geo plane (server/federation.py
+ the region_call envelope in server/cluster.py + the HTTP surface).

Scope here is the ROUTER: envelope kinds, retry/rerouting behavior,
fan-out idempotence, the federation status aggregation, the shed
redirect hint and the wan-reads boundary.  The full geo drill (region
kill, failover SLO, placement parity vs oracles) lives in
nomad_tpu/loadgen/geo_smoke.py and runs from tools/ci_check.sh.
"""
import json
import pickle
import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.api import start_http_server
from nomad_tpu.raft.transport import InmemTransport
from nomad_tpu.server.cluster import TestCluster
from nomad_tpu.server.federation import FederationError
from nomad_tpu.server.overload import MODE_SHEDDING
from nomad_tpu.structs import (
    Multiregion,
    MultiregionRegion,
)


def wait_until(pred, timeout=8.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


@pytest.fixture
def geo():
    transport = InmemTransport()
    east = TestCluster(
        3, transport=transport, region="east", name_prefix="east",
        heartbeat_ttl=60.0,
    )
    west = TestCluster(
        3, transport=transport, region="west", name_prefix="west",
        heartbeat_ttl=60.0,
    )
    east.start()
    west.start()
    west.servers[0].join(east.servers[0].addr)
    east_leader = east.wait_for_leader()
    west_leader = west.wait_for_leader()
    wait_until(
        lambda: len(east_leader.gossip.members_in_region("west")) == 3
        and len(west_leader.gossip.members_in_region("east")) == 3,
        msg="WAN membership convergence",
    )
    yield transport, east, west, east_leader, west_leader
    east.stop()
    west.stop()


def _mr_job(job_id, east_count, west_count):
    job = mock.job(id=job_id)
    job.task_groups[0].count = 1
    job.multiregion = Multiregion(
        regions=[
            MultiregionRegion(name="east", count=east_count),
            MultiregionRegion(name="west", count=west_count),
        ]
    )
    return job


# -- region_call envelope hardening -----------------------------------


def test_region_call_unknown_op_envelope(geo):
    _t, _e, _w, east_leader, _wl = geo
    resp = east_leader._handle_region_call(
        {
            "op": "definitely_not_an_op",
            "region": "east",
            "args": pickle.dumps(((), {})),
        }
    )
    assert resp["kind"] == "unknown_op"
    assert "definitely_not_an_op" in resp["error"]
    assert "result" not in resp


def test_region_call_wrong_region_envelope(geo):
    """Stale gossip can route a forward to a server that is not in
    the intended region; the answer must be structured (our region +
    leader hint), never an execution in the wrong region."""
    _t, _e, _w, east_leader, _wl = geo
    resp = east_leader._handle_region_call(
        {
            "op": "register_job",
            "region": "west",
            "args": pickle.dumps(((mock.job(id="misrouted"),), {})),
        }
    )
    assert resp["wrong_region"] is True
    assert resp["region"] == "east"
    assert resp["kind"] == "wrong_region"
    # the misrouted job must NOT have registered here
    assert east_leader.store.job_by_id("default", "misrouted") is None


def test_region_call_application_error_is_definitive(geo):
    """A validation failure from the remote leader comes back as a
    structured {error, kind: app} — and the router raises it without
    burning retries (the remote's verdict is replicated truth)."""
    _t, _e, _w, east_leader, west_leader = geo
    bad = mock.job(id="bad-job")
    bad.task_groups = []  # fails validation in the west leader
    retries_before = east_leader.metrics.get_counter(
        "federation.retries"
    )
    with pytest.raises(FederationError) as err:
        east_leader.federation.forward("west", "register_job", bad)
    assert err.value.kind == "app"
    assert (
        east_leader.metrics.get_counter("federation.retries")
        == retries_before
    )


def test_forward_unknown_region_exhausts_budget(geo):
    _t, _e, _w, east_leader, _wl = geo
    router = east_leader.federation
    router.retries, router.backoff_s = 1, 0.0  # fast budget for test
    with pytest.raises(FederationError) as err:
        router.forward("atlantis", "cluster_query", "metrics", None)
    assert err.value.kind == "unknown_region"


def test_forward_transport_failure_kind(geo):
    """Every west server unreachable from the east leader (but still
    rumored ALIVE by the rest of the pool): the forward must exhaust
    its budget with a transport-kind error, not hang or crash."""
    transport, _e, west, east_leader, _wl = geo
    router = east_leader.federation
    router.retries, router.backoff_s = 2, 0.0
    for srv in west.servers:
        transport.partition(east_leader.addr, srv.addr)
    try:
        with pytest.raises(FederationError) as err:
            router.forward("west", "cluster_query", "metrics", None)
        assert err.value.kind in ("transport", "timeout")
        assert east_leader.metrics.get_counter(
            "federation.rpc_errors"
        ) >= 3
    finally:
        transport.heal(east_leader.addr)


def test_forward_survives_remote_leader_kill(geo):
    """Mid-federation leadership loss in the target region: the
    bounded retry loop re-resolves membership / follows not_leader
    hints and the call still lands."""
    transport, _e, west, east_leader, west_leader = geo
    transport.set_down(west_leader.addr)
    try:
        wait_until(
            lambda: any(
                s.is_leader() and s._leader_established
                for s in west.servers
                if s is not west_leader
            ),
            msg="west re-election",
        )
        for _ in range(2):
            # a register_job forward must land on the NEW west leader
            job = mock.job(id=f"reroute-{_}")
            job.task_groups[0].count = 1
            job.region = "west"
            east_leader.federation.forward(
                "west", "register_job", job
            )
        new_leader = next(
            s
            for s in west.servers
            if s is not west_leader and s.is_leader()
        )
        assert new_leader.store.job_by_id("default", "reroute-0")
    finally:
        transport.set_down(west_leader.addr, down=False)


# -- fan-out: idempotence + per-region counts -------------------------


def test_federated_register_idempotent_per_cmd_id(geo):
    """The fan-out contract: a retried forward re-proposes the SAME
    per-region command id and must dedup in the target FSM — one job,
    one eval, no double scheduling."""
    _t, _e, _w, _el, west_leader = geo
    west_leader.register_node(mock.node())
    job = mock.job(id="fed-idem")
    job.task_groups[0].count = 1
    ev1 = west_leader.federated_register(job, "fanout-1:west")
    ev2 = west_leader.federated_register(
        mock.job(id="fed-idem"), "fanout-1:west"
    )
    assert ev1 is not None and ev2 is not None
    assert ev1.id == ev2.id  # deterministic eval id from the cmd id
    evals = [
        ev
        for ev in west_leader.store.evals.values()
        if ev.job_id == "fed-idem"
    ]
    assert len(evals) == 1
    stored = west_leader.store.job_by_id("default", "fed-idem")
    assert stored is not None and stored.version == 0


def test_multiregion_fanout_per_region_counts(geo):
    _t, east, _w, east_leader, west_leader = geo
    for _ in range(2):
        east_leader.register_node(mock.node())
        west_leader.register_node(mock.node())
    # submitted via an east FOLLOWER: home-routes to the east leader,
    # which fans out with per-region count overrides
    ev = east.followers()[0].register_job(_mr_job("geo-fan", 1, 2))
    assert ev is not None
    assert east_leader.drain_to_idle(timeout=10.0)
    assert west_leader.drain_to_idle(timeout=10.0)
    east_allocs = east_leader.store.allocs_by_job("default", "geo-fan")
    west_allocs = west_leader.store.allocs_by_job("default", "geo-fan")
    assert len([a for a in east_allocs if not a.terminal_status()]) == 1
    assert len([a for a in west_allocs if not a.terminal_status()]) == 2
    # each region interpolated its own copy
    assert east_leader.store.job_by_id("default", "geo-fan").region == "east"
    assert west_leader.store.job_by_id("default", "geo-fan").region == "west"


def test_federation_status_aggregates_regions(geo):
    _t, _e, _w, east_leader, west_leader = geo
    east_leader.register_node(mock.node())
    west_leader.register_node(mock.node())
    east_leader.register_job(_mr_job("geo-status", 1, 1))
    east_leader.drain_to_idle(timeout=10.0)
    west_leader.drain_to_idle(timeout=10.0)
    status = east_leader.federation.federation_status(
        "default", "geo-status"
    )
    assert status["home"] == "east"
    assert status["multiregion"] is True
    assert set(status["regions"]) == {"east", "west"}
    for name in ("east", "west"):
        view = status["regions"][name]
        assert view["registered"] is True
        assert view["region"] == name
        assert view["groups"] == {"web": 1}
        assert view["allocs"] == 1
    with pytest.raises(KeyError):
        east_leader.federation.federation_status("default", "no-such")


# -- region health table + shed redirect ------------------------------


def test_nearest_healthy_region_deterministic(geo):
    _t, east, west, east_leader, _wl = geo
    for i, srv in enumerate(west.servers):
        srv.advertise_http(f"127.0.0.1:91{i}")
    wait_until(
        lambda: len(
            east_leader.federation.refresh()
            .get("west", {})
            .get("http", [])
        )
        == 3,
        msg="http advertise rumors",
    )
    region, addr = east_leader.federation.nearest_healthy_region()
    assert region == "west"
    assert addr == "127.0.0.1:910"  # sorted-first: deterministic
    assert (
        east_leader.federation.http_addr_in("west") == "127.0.0.1:910"
    )
    assert east_leader.federation.http_addr_in("atlantis") is None


def test_shed_carries_retry_region_hint(geo, monkeypatch):
    """A SHEDDING region's 429 must point global traffic at the
    nearest healthy region (header + body), and count the redirect."""
    _t, _e, west, east_leader, _wl = geo
    for i, srv in enumerate(west.servers):
        srv.advertise_http(f"127.0.0.1:92{i}")
    wait_until(
        lambda: len(
            east_leader.federation.refresh()
            .get("west", {})
            .get("http", [])
        )
        == 3,
        msg="http advertise rumors",
    )
    monkeypatch.setattr(
        east_leader.overload,
        "evaluate",
        lambda force=False: MODE_SHEDDING,
    )
    http = start_http_server(east_leader, port=0)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/v1/jobs",
            data=json.dumps(
                {"Job": {"ID": "shed-me", "Type": "service",
                         "TaskGroups": [{"Name": "g", "Count": 1,
                                         "Tasks": [{"Name": "t",
                                                    "Driver": "mock_driver"}]}]}}
            ).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        resp = err.value
        assert resp.code == 429
        assert resp.headers["X-Nomad-Retry-Region"] == "west"
        assert (
            resp.headers["X-Nomad-Retry-Region-Addr"]
            == "127.0.0.1:920"
        )
        body = json.loads(resp.read())
        assert body["RetryRegion"] == "west"
        assert east_leader.metrics.get_counter(
            "federation.shed_redirects"
        ) >= 1
    finally:
        http.stop()


# -- the wan-reads boundary -------------------------------------------


def test_region_local_reads_never_cross_wan(geo):
    _t, _e, _w, east_leader, _wl = geo
    east_leader.cluster_query_region("metrics", None, region=None)
    east_leader.cluster_query_region("metrics", None, region="east")
    assert (
        east_leader.metrics.get_counter("federation.wan_reads") == 0
    )


def test_explicit_region_param_counts_wan_read(geo):
    _t, _e, _w, east_leader, west_leader = geo
    out = east_leader.cluster_query_region(
        "metrics", None, region="west"
    )
    assert east_leader.metrics.get_counter("federation.wan_reads") == 1
    # the merged answer comes from WEST's servers, not ours
    assert west_leader.addr in out["servers"]
    assert east_leader.addr not in out["servers"]


# -- HTTP federation endpoint -----------------------------------------


def test_http_job_federation_endpoint(geo):
    _t, _e, _w, east_leader, west_leader = geo
    east_leader.register_node(mock.node())
    west_leader.register_node(mock.node())
    east_leader.register_job(_mr_job("geo-http", 1, 1))
    east_leader.drain_to_idle(timeout=10.0)
    west_leader.drain_to_idle(timeout=10.0)
    http = start_http_server(east_leader, port=0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{http.port}/v1/job/geo-http/federation",
            timeout=10,
        ) as resp:
            payload = json.loads(resp.read())
        assert payload["home"] == "east"
        assert payload["regions"]["west"]["registered"] is True
        assert payload["regions"]["west"]["groups"] == {"web": 1}
        # unknown job -> 404, not a traceback
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}"
                "/v1/job/no-such/federation",
                timeout=10,
            )
        assert err.value.code == 404
    finally:
        http.stop()
