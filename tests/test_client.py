"""Client runtime tests: drivers, task/alloc runners, full client<->server
loop (reference model: drivers/mock tests, task_runner_test.go,
client_test.go).
"""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import AllocRunner, Client, TaskRunner
from nomad_tpu.client.drivers import MockDriver, RawExecDriver
from nomad_tpu.client.drivers.base import TaskConfig
from nomad_tpu.client.fingerprint import run_fingerprinters
from nomad_tpu.server import Server
from nomad_tpu.structs import (
    Node,
    RestartPolicy,
    Task,
)


def wait_until(cond, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def test_mock_driver_run_for_and_exit_code():
    d = MockDriver()
    h = d.start_task(
        TaskConfig(id="t1", config={"run_for": 0.05, "exit_code": 2})
    )
    res = d.wait_task("t1", timeout=2)
    assert res.exit_code == 2


def test_mock_driver_start_error():
    d = MockDriver()
    with pytest.raises(RuntimeError):
        d.start_task(TaskConfig(id="t1", config={"start_error": "boom"}))


def test_raw_exec_driver_real_process(tmp_path):
    d = RawExecDriver()
    cfg = TaskConfig(
        id="t1",
        name="echo",
        config={"command": "/bin/sh", "args": ["-c", "echo hi; exit 3"]},
        alloc_dir=str(tmp_path),
    )
    d.start_task(cfg)
    res = d.wait_task("t1", timeout=5)
    assert res.exit_code == 3
    out = (tmp_path / "echo.stdout").read_bytes()
    assert b"hi" in out


def test_raw_exec_driver_stop(tmp_path):
    d = RawExecDriver()
    cfg = TaskConfig(
        id="t1",
        name="sleep",
        config={"command": "/bin/sleep", "args": ["30"]},
        alloc_dir=str(tmp_path),
    )
    h = d.start_task(cfg)
    assert h.is_running()
    d.stop_task("t1", timeout=2)
    res = d.wait_task("t1", timeout=2)
    assert res is not None and res.signal != 0


# ---------------------------------------------------------------------------
# task runner
# ---------------------------------------------------------------------------


def _task(**config):
    return Task(name="t", driver="mock_driver", config=config)


def test_task_runner_completes():
    tr = TaskRunner(
        "alloc1",
        _task(run_for=0.05, exit_code=0),
        RestartPolicy(attempts=0, interval_s=10, delay_s=0.01),
        batch=True,
    )
    tr.start()
    assert tr.wait(5)
    assert tr.state.state == "dead"
    assert not tr.state.failed


def test_task_runner_restarts_then_fails():
    tr = TaskRunner(
        "alloc1",
        _task(run_for=0.01, exit_code=1),
        RestartPolicy(attempts=2, interval_s=100, delay_s=0.01, mode="fail"),
        batch=True,
    )
    tr.start()
    assert tr.wait(5)
    assert tr.state.failed
    # 1 initial + 2 restarts = 3 starts
    starts = [e for e in tr.state.events if e["type"] == "Started"]
    assert len(starts) == 3


def test_task_runner_kill():
    tr = TaskRunner(
        "alloc1",
        _task(run_for=-1),
        RestartPolicy(attempts=0, interval_s=10, delay_s=0.01),
        batch=False,
    )
    tr.start()
    assert wait_until(lambda: tr.is_running())
    tr.kill()
    assert tr.wait(5)
    assert tr.state.state == "dead"


# ---------------------------------------------------------------------------
# alloc runner
# ---------------------------------------------------------------------------


def test_alloc_runner_client_status_fanin():
    job = mock.job()
    job.task_groups[0].restart_policy = RestartPolicy(
        attempts=0, interval_s=10, delay_s=0.01, mode="fail"
    )
    job.task_groups[0].tasks[0] = Task(
        name="web", driver="mock_driver", config={"run_for": 0.05}
    )
    alloc = mock.alloc(job=job)
    updates = []
    runner = AllocRunner(alloc, on_update=lambda a: updates.append(a))
    runner.run()
    assert runner.wait(5)
    assert alloc.client_status == "complete"
    assert updates


def test_alloc_runner_failed_task_fails_alloc():
    job = mock.job()
    job.task_groups[0].restart_policy = RestartPolicy(
        attempts=0, interval_s=10, delay_s=0.01
    )
    job.task_groups[0].tasks[0] = Task(
        name="web", driver="mock_driver",
        config={"run_for": 0.02, "exit_code": 1},
    )
    alloc = mock.alloc(job=job)
    runner = AllocRunner(alloc)
    runner.run()
    assert runner.wait(5)
    assert alloc.client_status == "failed"


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------


def test_fingerprint_populates_node():
    n = Node()
    n.node_resources.cpu = 0
    n.node_resources.memory_mb = 0
    n.node_resources.disk_mb = 0
    run_fingerprinters(n, include_tpu=False)
    assert n.attributes["kernel.name"] == "linux"
    assert int(n.attributes["cpu.numcores"]) >= 1
    assert n.node_resources.cpu > 0
    assert n.node_resources.memory_mb > 0
    assert "unique.hostname" in n.attributes


# ---------------------------------------------------------------------------
# full client <-> server loop
# ---------------------------------------------------------------------------


@pytest.fixture
def cluster():
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=3)
    server.start()
    clients = []

    def add_client(**kwargs):
        node = mock.node()
        c = Client(
            server,
            node=node,
            fingerprint=False,
            heartbeat_interval=5.0,
            **kwargs,
        )
        c.start()
        clients.append(c)
        return c

    yield server, add_client
    for c in clients:
        c.stop()
    server.stop()


def test_client_runs_scheduled_job(cluster):
    server, add_client = cluster
    c1 = add_client()
    c2 = add_client()
    job = mock.job()
    job.task_groups[0].count = 2
    job.task_groups[0].tasks[0] = Task(
        name="web", driver="mock_driver", config={"run_for": -1}
    )
    server.register_job(job)
    assert server.drain_to_idle(10)
    assert wait_until(
        lambda: sum(
            a.client_status == "running"
            for a in server.store.allocs_by_job(job.namespace, job.id)
        )
        == 2,
        timeout=10,
    )


def test_client_batch_job_completes(cluster):
    server, add_client = cluster
    add_client()
    job = mock.batch_job()
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0] = Task(
        name="work", driver="mock_driver", config={"run_for": 0.05}
    )
    server.register_job(job)
    assert server.drain_to_idle(10)
    assert wait_until(
        lambda: any(
            a.client_status == "complete"
            for a in server.store.allocs_by_job(job.namespace, job.id)
        ),
        timeout=10,
    )
    # natural completion must free the node's tracked capacity: the
    # runner works on a DETACHED copy, so the upsert sees the
    # live->terminal flip and zeroes usage (ADVICE r4 / review r5 —
    # in-process aliasing defeated was_live before)
    alloc = server.store.allocs_by_job(job.namespace, job.id)[0]
    row = server.store.node_table.row_of[alloc.node_id]
    assert wait_until(
        lambda: server.store.node_table.cpu_used[row] == 0,
        timeout=10,
    )


def test_client_failed_alloc_reschedules(cluster):
    server, add_client = cluster
    add_client()
    add_client()
    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].restart_policy = RestartPolicy(
        attempts=0, interval_s=10, delay_s=0.01
    )
    from nomad_tpu.structs import ReschedulePolicy

    job.task_groups[0].reschedule_policy = ReschedulePolicy(
        attempts=3,
        interval_s=300,
        delay_s=0.0,
        delay_function="constant",
        unlimited=False,
    )
    job.task_groups[0].tasks[0] = Task(
        name="web", driver="mock_driver",
        config={"run_for": 0.05, "exit_code": 1},
    )
    server.register_job(job)
    # the failed alloc triggers an alloc-failure eval which replaces it
    assert wait_until(
        lambda: len(
            server.store.allocs_by_job(job.namespace, job.id)
        )
        >= 2,
        timeout=15,
    )


def test_client_stop_job_stops_tasks(cluster):
    server, add_client = cluster
    client = add_client()
    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0] = Task(
        name="web", driver="mock_driver", config={"run_for": -1}
    )
    server.register_job(job)
    assert wait_until(
        lambda: len(client.running_allocs()) == 1, timeout=10
    )
    server.deregister_job(job.namespace, job.id)
    assert wait_until(
        lambda: len(client.running_allocs()) == 0, timeout=10
    )


def test_driver_refingerprint_updates_node(cluster):
    """A driver whose daemon appears after boot flips the node's
    driver attribute (and class hash) on the periodic re-fingerprint;
    a driver that dies by RAISING reads as dead (reference
    FingerprintManager interval + updateNodeFromFingerprint)."""
    server, add_client = cluster
    c = add_client(watch_interval=0.05)
    c.heartbeat_interval = 0.05
    c.refingerprint_interval = 0.1

    class FlippyDriver:
        name = "flippy"
        healthy = False
        boom = False

        def fingerprint(self):
            if self.boom:
                raise RuntimeError("daemon gone")
            if self.healthy:
                return {
                    "driver.flippy": "1",
                    "driver.flippy.version": "9.9",
                }
            return {"driver.flippy": "0"}

    drv = FlippyDriver()
    c.drivers["flippy"] = drv
    class_before = c.node.computed_class
    drv.healthy = True

    def attr(key):
        n = server.store.node_by_id(c.node.id)
        return n.attributes.get(key) if n else None

    assert wait_until(
        lambda: attr("driver.flippy") == "1", timeout=10
    )
    assert attr("driver.flippy.version") == "9.9"
    # the class hash follows the attribute change so class-keyed
    # eligibility caches and blocked-eval unblocking see a new shape
    assert (
        server.store.node_by_id(c.node.id).computed_class
        != class_before
    )
    # a raising driver flips to dead AND its stale version key is
    # dropped (attribute replacement, not merge)
    drv.boom = True
    assert wait_until(
        lambda: attr("driver.flippy") == "0", timeout=10
    )
    assert attr("driver.flippy.version") is None
