"""Happens-before sanitizer (NOMAD_TPU_TSAN=1) — the runtime half of
the shared-state contract.

The static race detector (nomadlint ``shared-state-guard``) forces a
justified ``SHARED_STATE_ALLOWLIST`` entry for every deliberately
unguarded cross-thread attribute.  These tests keep that list honest
from the runtime direction:

- a 64-eval storm soak through the REAL pipeline (broker drain ->
  storm solve -> speculative replay -> commit) with the sanitizer on
  must observe ZERO conflicting access pairs outside the static
  allowlist — a pair outside both is a race one analysis missed;
- the detector itself is proven non-vacuous on a toy raced object
  (otherwise an instrumentation regression would green the soak by
  simply observing nothing).
"""
from __future__ import annotations

import threading

from nomad_tpu import tsan


def _allowed(conflict) -> bool:
    # the RULE's own matcher, so the soak and the static detector
    # can never drift on allowlist semantics
    from tools.nomadlint.rules.concurrency import _allowlisted

    return (
        _allowlisted(conflict["family"], conflict["attr"]) >= 0
    )


def test_tsan_detects_unordered_access(monkeypatch):
    """Sanity: the sanitizer must FLAG a genuinely raced attribute
    and stay quiet about a consistently locked one — the soak's
    zero-outside-allowlist assert is only meaningful if detection
    works."""
    monkeypatch.setenv("NOMAD_TPU_TSAN", "1")
    tsan.reset()

    class Toy:
        def __init__(self):
            self._lock = threading.Lock()
            self.guarded = 0
            self.racy = 0
            tsan.maybe_instrument(self, "TsanToy")

    toy = Toy()

    def loop():
        for _ in range(100):
            with toy._lock:
                toy.guarded += 1
            toy.racy += 1

    t = threading.Thread(target=loop, name="tsan-toy")
    t.start()
    for _ in range(100):
        with toy._lock:
            toy.guarded += 1
        toy.racy += 1
    t.join()

    found = {
        c["attr"]
        for c in tsan.conflicts()
        if c["family"] == "TsanToy"
    }
    assert "racy" in found
    assert "guarded" not in found
    tsan.reset()


def test_tsan_lock_ordering_suppresses_conflicts(monkeypatch):
    """Release/acquire edges order accesses: a value handed from one
    thread to another THROUGH a lock never conflicts."""
    monkeypatch.setenv("NOMAD_TPU_TSAN", "1")
    tsan.reset()

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self.value = 0
            tsan.maybe_instrument(self, "TsanBox")

    box = Box()

    def writer():
        for i in range(50):
            with box._lock:
                box.value = i

    t = threading.Thread(target=writer, name="tsan-box")
    t.start()
    for _ in range(50):
        with box._lock:
            _ = box.value
    t.join()
    assert [
        c for c in tsan.conflicts() if c["family"] == "TsanBox"
    ] == []
    tsan.reset()


def test_tsan_storm_soak_conflicts_within_allowlist(monkeypatch):
    """64-eval storm soak with the sanitizer on: the full pipeline
    (atomic family drain, device solve, speculative replay pool,
    incremental wave commit, broker sweeper, plan applier) runs
    instrumented, and every conflicting access pair observed at
    runtime must be lock-ordered or inside the STATIC allowlist."""
    from test_storm import (
        assert_zero_lost,
        family_jobs,
        placements,
        run_storm_server,
    )

    monkeypatch.setenv("NOMAD_TPU_TSAN", "1")
    monkeypatch.setenv("NOMAD_TPU_STORM", "1")
    monkeypatch.setenv("NOMAD_TPU_STORM_MIN", "8")
    tsan.reset()
    jobs = family_jobs(64, fam="tsanfam")
    server = run_storm_server(jobs, timeout=240)
    try:
        worker = server.workers[0]
        # the soak must exercise the real machinery, not idle past it
        assert worker.storm_solves >= 1
        for job in jobs:
            assert len(placements(server, job.id)) == 1
        assert_zero_lost(server, jobs)
    finally:
        server.stop()

    observed = tsan.conflicts()
    assert observed, (
        "the sanitizer observed NO conflicting pairs at all — the "
        "allowlisted GIL-atomic paths (StateStore lock-free reads, "
        "epoch-keyed cache flushes) run on every soak, so an empty "
        "log means instrumentation regressed, not that the code "
        "got race-free"
    )
    outside = [c for c in observed if not _allowed(c)]
    assert outside == [], (
        "runtime-observed conflicting access pairs OUTSIDE the "
        f"static SHARED_STATE_ALLOWLIST: {outside} — either a lock "
        "is missing or the static analysis needs a justified "
        "allowlist entry"
    )
    tsan.reset()
