"""job plan (dry-run), parameterized dispatch and log-proxy tests
(reference model: nomad/job_endpoint_test.go Plan/Dispatch,
client fs endpoint tests).
"""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client
from nomad_tpu.server import Server
from nomad_tpu.structs import Task


def wait_until(cond, timeout=10.0, interval=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def server():
    s = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=66)
    s.start()
    yield s
    s.stop()


def test_plan_new_job_annotations(server):
    for _ in range(3):
        server.register_node(mock.node())
    job = mock.job(id="planme")
    job.task_groups[0].count = 3
    result = server.plan_job(job)
    assert result["Diff"]["Type"] == "Added"
    assert result["Annotations"]["web"]["Place"] == 3
    # dry run: nothing committed
    assert not server.store.allocs_by_job("default", "planme")
    assert server.store.job_by_id("default", "planme") is None


def test_plan_update_shows_destructive(server):
    for _ in range(3):
        server.register_node(mock.node())
    job = mock.job(id="upd")
    job.task_groups[0].count = 2
    server.register_job(job)
    assert server.drain_to_idle(10)

    job2 = mock.job(id="upd")
    job2.task_groups[0].count = 2
    job2.task_groups[0].tasks[0].config = {"command": "/bin/other"}
    result = server.plan_job(job2)
    ann = result["Annotations"]["web"]
    assert ann["DestructiveUpdate"] == 2
    assert result["Diff"]["Type"] == "Edited"
    # live job untouched
    assert server.store.job_by_id("default", "upd").task_groups[0].tasks[
        0
    ].config == {"command": "/bin/date"}


def test_plan_reports_failed_placements(server):
    # no nodes: everything fails
    job = mock.job(id="nofit")
    result = server.plan_job(job)
    assert "web" in result["FailedTGAllocs"]
    assert not server.store.evals_by_job("default", "nofit")


def test_dispatch_parameterized_job(server):
    for _ in range(2):
        server.register_node(mock.node())
    parent = mock.batch_job(id="batcher")
    parent.task_groups[0].count = 1
    parent.parameterized = {
        "meta_required": ["input"],
        "meta_optional": ["verbose"],
    }
    server.register_job(parent)
    # parent creates no eval
    assert not server.store.evals_by_job("default", "batcher")

    with pytest.raises(ValueError):
        server.dispatch_job("default", "batcher", meta={})
    with pytest.raises(ValueError):
        server.dispatch_job(
            "default", "batcher", meta={"input": "x", "bogus": "y"}
        )

    child = server.dispatch_job(
        "default", "batcher", meta={"input": "s3://bucket"}
    )
    assert child.parent_id == "batcher"
    assert child.meta["input"] == "s3://bucket"
    assert server.drain_to_idle(10)
    assert server.store.allocs_by_job("default", child.id)


def test_alloc_log_proxy(server, tmp_path):
    client = Client(
        server,
        node=mock.node(),
        data_dir=str(tmp_path),
        fingerprint=False,
        drivers=["raw_exec", "mock_driver", "exec"],
    )
    client.start()
    try:
        job = mock.job(id="logger")
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0] = Task(
            name="speak",
            driver="raw_exec",
            config={
                "command": "/bin/sh",
                "args": ["-c", "echo hello-from-task; sleep 30"],
            },
        )
        server.register_job(job)
        assert server.drain_to_idle(10)
        allocs = server.store.allocs_by_job("default", "logger")
        assert wait_until(
            lambda: b"hello-from-task"
            in server.read_task_log(allocs[0].id, "speak", "stdout"),
            timeout=10,
        )
    finally:
        client.stop()
