"""Batched eval pipeline tests: the prescored path must produce plans
identical to the sequential scheduler and fall back safely.
"""
import copy
import random
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server
from nomad_tpu.structs import compute_node_class


def make_nodes(n, seed=0):
    rng = random.Random(seed)
    nodes = []
    for _ in range(n):
        node = mock.node()
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.node_resources.memory_mb = rng.choice([8192, 16384])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    return nodes


def make_jobs(n, seed=1):
    rng = random.Random(seed)
    jobs = []
    for i in range(n):
        job = mock.job(id=f"batch-pipe-{i}")
        job.task_groups[0].count = rng.randint(1, 5)
        job.task_groups[0].tasks[0].resources.cpu = rng.choice([200, 500])
        jobs.append(job)
    return jobs


def placements(server, job_id):
    return sorted(
        (a.name, a.node_id)
        for a in server.store.allocs_by_job("default", job_id)
        if not a.terminal_status()
    )


def test_batch_pipeline_matches_sequential():
    nodes = make_nodes(20)
    jobs = make_jobs(8)

    seq = Server(num_schedulers=1, seed=99, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=99, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        for job in jobs:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(15)
        for job in jobs:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(30)

        for job in jobs:
            assert placements(seq, job.id) == placements(bat, job.id), (
                f"divergence for {job.id}"
            )
        worker = bat.workers[0]
        assert worker.prescored > 0
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_fallback_for_complex_evals():
    """Evals the prescorer cannot handle still complete correctly."""
    from nomad_tpu.structs import Spread, SpreadTarget

    server = Server(num_schedulers=1, seed=7, batch_pipeline=True)
    server.start()
    try:
        for node in make_nodes(10, seed=3):
            server.register_node(node)
        # spread job: not batchable
        job = mock.job(id="spready")
        job.task_groups[0].count = 4
        job.spreads = [Spread(attribute="${node.datacenter}", weight=50)]
        server.register_job(job)
        assert server.drain_to_idle(15)
        assert len(placements(server, "spready")) == 4

        # scale-up of an existing job: not batchable (live allocs)
        job2 = mock.job(id="grower")
        job2.task_groups[0].count = 2
        server.register_job(job2)
        assert server.drain_to_idle(15)
        job3 = mock.job(id="grower")
        job3.task_groups[0].count = 4
        server.register_job(job3)
        assert server.drain_to_idle(15)
        assert len(placements(server, "grower")) == 4
    finally:
        server.stop()


def test_batch_pipeline_blocked_eval_on_exhaustion():
    server = Server(num_schedulers=1, seed=8, batch_pipeline=True)
    server.start()
    try:
        node = mock.node()
        node.node_resources.cpu = 1000
        node.node_resources.memory_mb = 1024
        node.computed_class = compute_node_class(node)
        server.register_node(node)
        job = mock.job(id="toolarge")
        job.task_groups[0].count = 5
        job.task_groups[0].tasks[0].resources.cpu = 400
        server.register_job(job)
        assert server.drain_to_idle(15)

        def settled():
            placed = placements(server, "toolarge")
            return (
                0 < len(placed) < 5
                and server.blocked.blocked_count() >= 1
            )

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not settled():
            time.sleep(0.05)
        assert settled()
    finally:
        server.stop()


def test_batch_pipeline_spread_in_kernel_matches_sequential():
    """Percent-target spread jobs run through the in-kernel carry and
    produce placements identical to the sequential scheduler
    (spread.go:163 boost semantics, SpreadInputs in ops/batch.py)."""
    from nomad_tpu.structs import Affinity, Spread, SpreadTarget

    rng = random.Random(5)
    nodes = []
    for i in range(24):
        node = mock.node()
        node.datacenter = rng.choice(["dc1", "dc2", "dc3"])
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.node_resources.memory_mb = rng.choice([8192, 16384])
        node.computed_class = compute_node_class(node)
        nodes.append(node)

    def spread_job(i):
        job = mock.job(id=f"spread-{i}")
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = 6
        tg.tasks[0].resources.cpu = 300
        job.spreads = [
            Spread(
                attribute="${node.datacenter}",
                weight=60,
                targets=[
                    SpreadTarget(value="dc1", percent=50),
                    SpreadTarget(value="dc2", percent=30),
                    # dc3 via the implicit "*" remainder
                ],
            )
        ]
        if i % 2:
            job.affinities = [
                Affinity(
                    ltarget="${node.datacenter}",
                    operand="=",
                    rtarget="dc2",
                    weight=40,
                )
            ]
        return job

    jobs = [spread_job(i) for i in range(6)]
    # plus interleaved plain jobs: mixed batches must stack correctly
    plain = make_jobs(3, seed=9)

    seq = Server(num_schedulers=1, seed=42, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=42, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        for job in jobs + plain:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(20)
        for job in jobs + plain:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(40)

        for job in jobs + plain:
            assert placements(seq, job.id) == placements(bat, job.id), (
                f"divergence for {job.id}"
            )
        worker = bat.workers[0]
        assert worker.prescored >= len(jobs) + len(plain), (
            f"spread jobs fell back: prescored={worker.prescored} "
            f"fallbacks={worker.fallbacks}"
        )
        # distribution sanity: dc1 got the most (50% target)
        by_dc = {}
        node_dc = {n.id: n.datacenter for n in nodes}
        for _name, node_id in placements(bat, "spread-0"):
            by_dc[node_dc[node_id]] = by_dc.get(node_dc[node_id], 0) + 1
        assert by_dc.get("dc1", 0) >= max(by_dc.values()) - 1
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_even_spread_in_kernel_matches():
    """Even-spread mode (no targets) runs in-kernel: min/max balance
    boosts over the observed use map, bit-identical to the sequential
    SpreadIterator (spread.py even_spread_score_boost)."""
    import random as _random

    from nomad_tpu.structs import Spread

    nodes = make_nodes(12, seed=3)
    rng = _random.Random(5)
    for n in nodes:
        n.datacenter = rng.choice(["dc1", "dc2", "dc3"])
        n.computed_class = compute_node_class(n)

    def even_job(i, count):
        job = mock.job(
            id=f"even-{i}", datacenters=["dc1", "dc2", "dc3"]
        )
        job.task_groups[0].count = count
        job.spreads = [
            Spread(attribute="${node.datacenter}", weight=50)
        ]
        return job

    seq = Server(num_schedulers=1, seed=7, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=7, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        jobs = [even_job(i, 3 + i) for i in range(4)]
        for job in jobs:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(30)
        for job in jobs:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(60)
        for job in jobs:
            assert placements(seq, job.id) == placements(bat, job.id), (
                job.id
            )
        worker = bat.workers[0]
        assert worker.prescored >= 1, (
            worker.prescored, worker.fallbacks,
        )
        # scale-up: steady-state even-spread (live allocs feed the
        # use map) stays identical too
        for server in (seq, bat):
            grown = even_job(0, 8)
            grown.version = 1
            server.register_job(grown)
        assert seq.drain_to_idle(30)
        assert bat.drain_to_idle(60)
        assert placements(seq, "even-0") == placements(bat, "even-0")
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_mixed_percent_and_even_spreads_match():
    """A job mixing a percent-target stanza with an even stanza on a
    different attribute exercises both kernel paths at once."""
    import random as _random

    from nomad_tpu.structs import Spread, SpreadTarget

    nodes = make_nodes(12, seed=9)
    rng = _random.Random(11)
    for n in nodes:
        n.datacenter = rng.choice(["dc1", "dc2"])
        n.attributes["rack"] = rng.choice(["r0", "r1", "r2"])
        n.computed_class = compute_node_class(n)

    def mixed_job(count):
        job = mock.job(id="mixed", datacenters=["dc1", "dc2"])
        job.task_groups[0].count = count
        job.spreads = [
            Spread(
                attribute="${node.datacenter}",
                weight=60,
                targets=[
                    SpreadTarget(value="dc1", percent=70),
                    SpreadTarget(value="dc2", percent=30),
                ],
            ),
            Spread(attribute="${attr.rack}", weight=40),
        ]
        return job

    seq = Server(num_schedulers=1, seed=13, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=13, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        seq.register_job(mixed_job(6))
        assert seq.drain_to_idle(30)
        bat.register_job(mixed_job(6))
        assert bat.drain_to_idle(60)
        assert placements(seq, "mixed") == placements(bat, "mixed")
        worker = bat.workers[0]
        assert worker.prescored >= 1, (
            worker.prescored, worker.fallbacks,
        )
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_duplicate_spread_attribute_matches():
    """Job- and group-level spreads on the same attribute: the
    attribute-keyed info map double-applies the overwrite winner
    (reference computeSpreadInfo semantics) — the kernel must match."""
    from nomad_tpu.structs import Spread, SpreadTarget

    rng = random.Random(11)
    nodes = []
    for _ in range(18):
        node = mock.node()
        node.datacenter = rng.choice(["dc1", "dc2", "dc3"])
        node.computed_class = compute_node_class(node)
        nodes.append(node)

    job = mock.job(id="dup-spread")
    job.datacenters = ["dc1", "dc2", "dc3"]
    tg = job.task_groups[0]
    tg.count = 6
    job.spreads = [
        Spread(
            attribute="${node.datacenter}",
            weight=80,
            targets=[SpreadTarget(value="dc1", percent=70)],
        )
    ]
    tg.spreads = [
        Spread(
            attribute="${node.datacenter}",
            weight=20,
            targets=[SpreadTarget(value="dc2", percent=60)],
        )
    ]

    seq = Server(num_schedulers=1, seed=13, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=13, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(20)
        bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(20)
        assert placements(seq, "dup-spread") == placements(
            bat, "dup-spread"
        )
        assert bat.workers[0].prescored >= 1
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_steady_state_churn_matches_sequential():
    """The VERDICT r1 target: a mixed churn stream — new jobs,
    scale-ups, node-down reschedules, failed-alloc reschedules with
    penalty nodes — prescores the large majority of evals with plans
    bit-identical to the sequential worker (generic_sched.go:332
    computeJobAllocs semantics end to end)."""
    from nomad_tpu.structs import ReschedulePolicy

    nodes = make_nodes(24, seed=21)
    jobs = make_jobs(8, seed=22)
    for j in jobs:
        j.task_groups[0].reschedule_policy = ReschedulePolicy(
            delay_s=0.0, unlimited=True
        )

    seq = Server(num_schedulers=1, seed=77, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=77, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        for job in jobs:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(20)
        for job in jobs:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(40)
        for job in jobs:
            assert placements(seq, job.id) == placements(bat, job.id), (
                f"phase-1 divergence for {job.id}"
            )

        # -- phase 2: churn ------------------------------------------
        def churn(server):
            # scale-ups (steady-state evals over live allocs)
            for i in (0, 2, 5):
                grown = copy.deepcopy(jobs[i])
                grown.task_groups[0].count += 3
                server.register_job(grown)
            # brand-new jobs interleaved
            for k in range(2):
                nj = mock.job(id=f"churn-new-{k}")
                nj.task_groups[0].count = 2
                server.register_job(nj)
            # drain BEFORE the node dies: a node-down racing an
            # in-flight eval gives the two servers legitimately
            # different interleavings (whether the eval's snapshot sees
            # the node ready is timing), and bit-identity is only
            # defined per interleaving
            assert server.drain_to_idle(30)
            # a node dies: its allocs go lost and reschedule
            server.update_node_status(nodes[3].id, "down")

        churn(seq)
        assert seq.drain_to_idle(20)
        churn(bat)
        assert bat.drain_to_idle(40)

        all_ids = [j.id for j in jobs] + ["churn-new-0", "churn-new-1"]
        for jid in all_ids:
            assert placements(seq, jid) == placements(bat, jid), (
                f"phase-2 divergence for {jid}"
            )

        # -- phase 3: failed allocs reschedule with penalty ----------
        def fail_alloc(server, job_id, name):
            for a in server.store.allocs_by_job("default", job_id):
                if a.name == name and not a.terminal_status():
                    failed = copy.deepcopy(a)
                    failed.client_status = "failed"
                    server.update_allocs_from_client([failed])
                    return
            raise AssertionError(f"no live alloc {name}")

        victims = [
            (jobs[1].id, placements(seq, jobs[1].id)[0][0]),
            (jobs[4].id, placements(seq, jobs[4].id)[0][0]),
        ]
        for jid, name in victims:
            fail_alloc(seq, jid, name)
        assert seq.drain_to_idle(20)
        for jid, name in victims:
            fail_alloc(bat, jid, name)
        assert bat.drain_to_idle(40)

        for jid in all_ids:
            assert placements(seq, jid) == placements(bat, jid), (
                f"phase-3 divergence for {jid}"
            )

        worker = bat.workers[0]
        total = worker.prescored + worker.fallbacks
        assert total > 0
        rate = worker.prescored / total
        assert rate > 0.8, (
            f"steady-state prescore rate too low: {worker.prescored}/"
            f"{total} = {rate:.2f}"
        )
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_distinct_hosts_matches_sequential():
    """distinct_hosts jobs prescore (the kernel's collision carry IS
    the proposed-allocs-per-node count for single-TG jobs) and match
    the sequential scheduler bit for bit — including a scale-up where
    existing allocs exclude their nodes (feasible.go:470)."""
    import copy

    from nomad_tpu.structs import Constraint

    nodes = make_nodes(12, seed=31)

    def dh_job(count):
        job = mock.job(id="dh-job")
        tg = job.task_groups[0]
        tg.count = count
        tg.tasks[0].resources.cpu = 200
        job.constraints = list(job.constraints) + [
            Constraint(operand="distinct_hosts")
        ]
        return job

    seq = Server(num_schedulers=1, seed=41, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=41, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        for srv in (seq, bat):
            srv.register_job(dh_job(5))
            assert srv.drain_to_idle(20)
        assert placements(seq, "dh-job") == placements(bat, "dh-job")
        # all on distinct nodes
        node_ids = [n for _, n in placements(bat, "dh-job")]
        assert len(set(node_ids)) == 5

        # scale up: existing allocs must exclude their nodes
        for srv in (seq, bat):
            srv.register_job(dh_job(9))
            assert srv.drain_to_idle(20)
        assert placements(seq, "dh-job") == placements(bat, "dh-job")
        node_ids = [n for _, n in placements(bat, "dh-job")]
        assert len(node_ids) == 9 and len(set(node_ids)) == 9
        worker = bat.workers[0]
        assert worker.prescored >= 1, (
            worker.prescored, worker.fallbacks, worker.errors,
        )
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_steady_state_spread_matches_sequential():
    """Scale-ups and reschedules of percent-target spread jobs stay on
    the prescored path: the kernel's existing/cleared carries reproduce
    propertySet.GetCombinedUseMap (propertyset.go) including the
    PopulateProposed cleared-decrement quirk."""
    import copy

    from nomad_tpu.structs import Spread, SpreadTarget

    rng = random.Random(51)
    nodes = []
    for _ in range(18):
        node = mock.node()
        node.datacenter = rng.choice(["dc1", "dc2", "dc3"])
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.computed_class = compute_node_class(node)
        nodes.append(node)

    def spread_job(count, cpu=250):
        job = mock.job(id="ss-spread")
        job.datacenters = ["dc1", "dc2", "dc3"]
        tg = job.task_groups[0]
        tg.count = count
        tg.tasks[0].resources.cpu = cpu
        job.spreads = [
            Spread(
                attribute="${node.datacenter}",
                weight=70,
                targets=[
                    SpreadTarget(value="dc1", percent=60),
                    SpreadTarget(value="dc2", percent=20),
                ],
            )
        ]
        return job

    seq = Server(num_schedulers=1, seed=61, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=61, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        # initial placement, then a scale-up (existing allocs feed
        # used0), then a destructive update (cpu bump -> evictions feed
        # the cleared carry per pick)
        node_dc = {n.id: n.datacenter for n in nodes}
        for count, cpu in ((4, 250), (9, 250), (9, 400)):
            for srv in (seq, bat):
                srv.register_job(spread_job(count, cpu))
                assert srv.drain_to_idle(25)
            ps = placements(seq, "ss-spread")
            pb = placements(bat, "ss-spread")
            assert ps == pb, (
                f"divergence at count={count} cpu={cpu}: "
                f"seq={[(n, node_dc[i]) for n, i in ps]} "
                f"bat={[(n, node_dc[i]) for n, i in pb]} "
                f"prescored={bat.workers[0].prescored} "
                f"fallbacks={bat.workers[0].fallbacks}"
            )
        worker = bat.workers[0]
        assert worker.prescored >= 2, (
            worker.prescored, worker.fallbacks, worker.errors,
        )
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_network_jobs_match_sequential():
    """Host-mode dynamic-port jobs ride the fast path: the kernel is
    port-blind but the winner's exact verification assigns real ports,
    so plans match the sequential worker bit-for-bit."""
    from nomad_tpu.structs import NetworkResource, Port

    nodes = make_nodes(12, seed=31)
    jobs = make_jobs(4, seed=32)
    for j in jobs:
        j.task_groups[0].networks = [
            NetworkResource(
                dynamic_ports=[Port("http"), Port("admin")]
            )
        ]

    seq = Server(num_schedulers=1, seed=55, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=55, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        for job in jobs:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(20)
        for job in jobs:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(40)
        for job in jobs:
            assert placements(seq, job.id) == placements(bat, job.id)
        # the network jobs actually used the fast path
        worker = bat.workers[0]
        assert worker.prescored >= 1, (
            worker.prescored,
            worker.fallbacks,
        )
        # placed allocs carry real port assignments
        some = [
            a
            for a in bat.store.allocs_by_job("default", jobs[0].id)
            if not a.terminal_status()
        ]
        assert some
        for a in some:
            ports = a.allocated_resources.shared.ports
            assert {p.label for p in ports} == {"http", "admin"}
            assert all(p.value > 0 for p in ports)
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_static_port_contention_identical():
    """Reserved-port jobs take the sequential path (a port-collided
    node is skipped by binpack without consuming a walk-limit slot —
    an asymmetry the kernel can't see), and outcomes stay identical,
    including the blocked eval when every node's port is taken."""
    from nomad_tpu.structs import NetworkResource, Port

    nodes = make_nodes(3, seed=41)

    def static_job(jid, count):
        job = mock.job(id=jid)
        job.task_groups[0].count = count
        job.task_groups[0].tasks[0].resources.cpu = 100
        job.task_groups[0].networks = [
            NetworkResource(reserved_ports=[Port("svc", 8080)])
        ]
        return job

    seq = Server(num_schedulers=1, seed=66, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=66, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        for server in (seq, bat):
            server.register_job(static_job("port-a", 3))
        assert seq.drain_to_idle(20)
        assert bat.drain_to_idle(20)
        assert placements(seq, "port-a") == placements(bat, "port-a")
        assert len(placements(bat, "port-a")) == 3
        # every node's 8080 is now taken: the second job must block on
        # both servers
        for server in (seq, bat):
            server.register_job(static_job("port-b", 1))
        assert seq.drain_to_idle(20)
        assert bat.drain_to_idle(20)
        assert placements(seq, "port-b") == placements(bat, "port-b")
        assert placements(bat, "port-b") == []
        for server in (seq, bat):
            evs = server.store.evals_by_job("default", "port-b")
            assert any(e.status == "blocked" for e in evs)
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_even_mode_edge_cases_match():
    """Review regressions: (a) duplicate attribute with mixed target
    presence follows the merged info's mode on both paths; (b) an
    even-spread job whose update stages destructive evictions (cleared
    can zero a use-map value, where the oracle's zero-reset min/max
    idiom is iteration-order dependent) falls back to the exact path —
    outcomes identical either way."""
    import random as _random

    from nomad_tpu.structs import Spread, SpreadTarget

    nodes = make_nodes(10, seed=17)
    rng = _random.Random(19)
    for n in nodes:
        n.datacenter = rng.choice(["dc1", "dc2"])
        n.computed_class = compute_node_class(n)

    # (a) tg stanza has targets, job stanza (overwrite winner) does
    # not -> sequential scores BOTH psets in even mode
    def dup_job(count):
        job = mock.job(id="dup-mode", datacenters=["dc1", "dc2"])
        job.task_groups[0].count = count
        job.task_groups[0].spreads = [
            Spread(
                attribute="${node.datacenter}",
                weight=70,
                targets=[SpreadTarget(value="dc1", percent=80)],
            )
        ]
        job.spreads = [
            Spread(attribute="${node.datacenter}", weight=30)
        ]
        return job

    seq = Server(num_schedulers=1, seed=23, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=23, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        seq.register_job(dup_job(5))
        assert seq.drain_to_idle(30)
        bat.register_job(dup_job(5))
        assert bat.drain_to_idle(60)
        assert placements(seq, "dup-mode") == placements(
            bat, "dup-mode"
        )

        # (b) destructive update on an even-spread job: new config
        # forces stop+replace; batch must fall back yet match
        def even_destr(version):
            job = mock.job(id="even-destr", datacenters=["dc1", "dc2"])
            job.task_groups[0].count = 4
            job.spreads = [
                Spread(attribute="${node.datacenter}", weight=50)
            ]
            if version:
                job.task_groups[0].tasks[0].config = {
                    "command": "/bin/true"
                }
                job.version = version
            return job

        for server in (seq, bat):
            server.register_job(even_destr(0))
        assert seq.drain_to_idle(30)
        assert bat.drain_to_idle(60)
        assert placements(seq, "even-destr") == placements(
            bat, "even-destr"
        )
        for server in (seq, bat):
            server.register_job(even_destr(1))
        assert seq.drain_to_idle(30)
        assert bat.drain_to_idle(60)
        assert placements(seq, "even-destr") == placements(
            bat, "even-destr"
        )
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_multi_task_group_matches_sequential():
    """Multi-task-group jobs run the prescored path (per-pick group
    routing, ops/batch.py TGInputs) bit-identically to the sequential
    scheduler: the walk offset continues across groups within one eval
    (reference generic_sched.go:468 computePlacements iterating task
    groups), asks/feasibility/anti-affinity are per group."""
    import dataclasses

    from nomad_tpu.structs import Task, TaskGroup

    def add_group(job, name, count, cpu, mem, driver="mock_driver"):
        tg0 = job.task_groups[0]
        tg = TaskGroup(
            name=name,
            count=count,
            restart_policy=tg0.restart_policy,
            reschedule_policy=tg0.reschedule_policy,
            tasks=[
                Task(
                    name=f"{name}-task",
                    driver=driver,
                    resources=dataclasses.replace(
                        tg0.tasks[0].resources,
                        cpu=cpu,
                        memory_mb=mem,
                    ),
                )
            ],
            ephemeral_disk=tg0.ephemeral_disk,
        )
        job.task_groups.append(tg)

    def make_stream():
        rng = random.Random(7)
        jobs = []
        for i in range(10):
            job = mock.job(id=f"mtg-{i}")
            job.task_groups[0].count = rng.randint(1, 4)
            job.task_groups[0].tasks[0].resources.cpu = rng.choice(
                [200, 500]
            )
            if i % 3 != 2:  # mixed stream: mostly multi-group
                add_group(
                    job, "api", rng.randint(1, 3),
                    rng.choice([300, 700]), 512,
                )
            if i % 4 == 1:  # three groups
                add_group(job, "cache", 2, 250, 256)
            jobs.append(job)
        return jobs

    nodes = make_nodes(24, seed=5)
    seq = Server(num_schedulers=1, seed=41, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=41, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        jobs = make_stream()
        for job in jobs:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(30)
        for job in jobs:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(60)

        for job in jobs:
            assert placements(seq, job.id) == placements(
                bat, job.id
            ), f"divergence for {job.id}"
        worker = bat.workers[0]
        total = worker.prescored + worker.fallbacks
        assert total > 0
        rate = worker.prescored / total
        assert rate > 0.8, (
            f"multi-group stream prescore rate too low: "
            f"{worker.prescored}/{total}"
        )
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_multi_tg_failure_coalescing_matches():
    """Per-group failure coalescing: a group whose ask exceeds every
    node fails while its sibling group keeps placing — bit-identical
    to the sequential path (generic_sched.go:482 coalesces failures
    PER task group)."""
    import dataclasses

    from nomad_tpu.structs import Task, TaskGroup

    nodes = make_nodes(12, seed=9)
    seq = Server(num_schedulers=1, seed=13, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=13, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))

        def giant_job():
            job = mock.job(id="mtg-fail")
            tg0 = job.task_groups[0]
            tg0.count = 3
            tg0.tasks[0].resources.cpu = 300
            giant = TaskGroup(
                name="giant",
                count=2,
                restart_policy=tg0.restart_policy,
                reschedule_policy=tg0.reschedule_policy,
                tasks=[
                    Task(
                        name="giant-task",
                        driver="mock_driver",
                        resources=dataclasses.replace(
                            tg0.tasks[0].resources,
                            cpu=50_000,  # no node fits
                            memory_mb=512,
                        ),
                    )
                ],
                ephemeral_disk=tg0.ephemeral_disk,
            )
            # giant placed between web groups in the placement stream
            job.task_groups.append(giant)
            return job

        for server in (seq, bat):
            server.register_job(giant_job())
        assert seq.drain_to_idle(30)
        assert bat.drain_to_idle(60)
        assert placements(seq, "mtg-fail") == placements(
            bat, "mtg-fail"
        )
        # the web group placed, the giant group failed on both paths
        seq_evals = seq.store.evals_by_job("default", "mtg-fail")
        bat_evals = bat.store.evals_by_job("default", "mtg-fail")
        def failed_tgs(evs):
            return sorted(
                {
                    name
                    for e in evs
                    for name in (e.failed_tg_allocs or {})
                }
            )
        assert failed_tgs(seq_evals) == failed_tgs(bat_evals)
        assert "giant" in failed_tgs(bat_evals)
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_multi_tg_steady_state_matches():
    """Steady-state multi-group churn (version bump -> destructive
    updates across BOTH groups in one eval) stays bit-identical and
    prescored."""
    import dataclasses

    from nomad_tpu.structs import Task, TaskGroup

    nodes = make_nodes(20, seed=11)
    seq = Server(num_schedulers=1, seed=23, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=23, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))

        def versioned(version):
            job = mock.job(id="mtg-churn", type="batch")
            tg0 = job.task_groups[0]
            tg0.count = 3
            tg0.tasks[0].resources.cpu = 400
            api = TaskGroup(
                name="api",
                count=2,
                restart_policy=tg0.restart_policy,
                reschedule_policy=tg0.reschedule_policy,
                tasks=[
                    Task(
                        name="api-task",
                        driver="mock_driver",
                        resources=dataclasses.replace(
                            tg0.tasks[0].resources,
                            cpu=600,
                            memory_mb=512,
                        ),
                    )
                ],
                ephemeral_disk=tg0.ephemeral_disk,
            )
            job.task_groups.append(api)
            if version:
                for tg in job.task_groups:
                    tg.tasks[0].config = {"command": "/bin/true"}
                job.version = version
            return job

        for server in (seq, bat):
            server.register_job(versioned(0))
        assert seq.drain_to_idle(30)
        assert bat.drain_to_idle(60)
        assert placements(seq, "mtg-churn") == placements(
            bat, "mtg-churn"
        )
        # destructive update across both groups in one eval
        for server in (seq, bat):
            server.register_job(versioned(1))
        assert seq.drain_to_idle(30)
        assert bat.drain_to_idle(60)
        assert placements(seq, "mtg-churn") == placements(
            bat, "mtg-churn"
        )
        assert bat.workers[0].prescored >= 2, (
            bat.workers[0].prescored,
            bat.workers[0].fallbacks,
        )
    finally:
        seq.stop()
        bat.stop()


def test_warm_shapes_are_recognized_by_launch_gate(monkeypatch):
    """warm_shapes must register signatures under the same key
    _launch_ready looks up (fn-name prefix included) — otherwise every
    pre-warmed shape still counts a cold_shape_fallback on first
    production sighting and the warm-up is defeated."""
    monkeypatch.delenv("NOMAD_TPU_SYNC_COMPILE", raising=False)
    bat = Server(num_schedulers=1, seed=3, batch_pipeline=True)
    bat.start()
    try:
        bat.register_node(mock.node())
        worker = bat.workers[0]
        worker.warm_shapes(
            e_buckets=(8,), p_buckets=(16,), t_buckets=(1,)
        )
        table = bat.store.node_table
        inert = worker._inert_inputs(table, P=16, T=1)
        import numpy as np
        stacked = type(inert)(
            *[
                np.stack([getattr(inert, f)] * 8)
                for f in type(inert)._fields
            ]
        )
        args = (
            table.cpu_total, table.mem_total, table.disk_total,
            table.cpu_used, table.mem_used, table.disk_used,
            stacked, np.full(8, 1, np.int32), 16,
        )
        kwargs = dict(
            spread_fit=False, wanted=np.zeros(8, np.int32),
            coll0=None, affinity=None, spread=None,
            deltas=worker._zero_deltas(8, 16),
            pre=worker._zero_pre(8),
            # production chunk launches always ask for the carry
            return_carry=True,
        )
        assert worker._launch_ready(args, kwargs), (
            "pre-warmed launch shape not recognized"
        )
    finally:
        bat.stop()


def test_batch_pipeline_static_ports_match_sequential():
    """Reserved/static host ports run the prescored path with the
    kernel's walk-slot-neutral collision mask (ops/batch.py
    PortInputs): contended static ports produce placements
    bit-identical to the sequential scheduler (rank.go network path
    skips collided nodes without consuming a walk-limit slot)."""
    from nomad_tpu.structs import NetworkResource, Port

    nodes = make_nodes(10, seed=3)
    seq = Server(num_schedulers=1, seed=77, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=77, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))

        # three jobs fighting over :8080 (each instance needs the
        # port exclusively per node) + one uncontended + one portless
        jobs = []
        for i in range(3):
            job = mock.job(id=f"port-{i}")
            tg = job.task_groups[0]
            tg.count = 3
            tg.tasks[0].resources.cpu = 200
            tg.networks = [
                NetworkResource(
                    mode="host",
                    reserved_ports=[Port(label="http", value=8080)],
                )
            ]
            jobs.append(job)
        other = mock.job(id="port-other")
        other.task_groups[0].count = 2
        other.task_groups[0].networks = [
            NetworkResource(
                mode="host",
                reserved_ports=[Port(label="admin", value=9443)],
            )
        ]
        jobs.append(other)
        plain = mock.job(id="port-plain")
        plain.task_groups[0].count = 2
        jobs.append(plain)

        for job in jobs:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(30)
        for job in jobs:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(60)

        for job in jobs:
            assert placements(seq, job.id) == placements(
                bat, job.id
            ), f"divergence for {job.id}"
        # :8080 really is exclusive per node
        holders = [
            a.node_id
            for i in range(3)
            for a in bat.store.allocs_by_job(
                "default", f"port-{i}"
            )
            if not a.terminal_status()
        ]
        assert len(holders) == len(set(holders)), holders
        worker = bat.workers[0]
        assert worker.prescored >= 3, (
            worker.prescored, worker.fallbacks, worker.errors,
        )
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_static_port_exhaustion_and_release():
    """Port exhaustion fails identically on both paths, and a port
    released by stopping a job is reusable afterwards (the release
    gate in _flush_run keeps the monotone kernel carry exact)."""
    from nomad_tpu.structs import NetworkResource, Port

    nodes = make_nodes(4, seed=21)
    seq = Server(num_schedulers=1, seed=31, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=31, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))

        def port_job(jid, count):
            job = mock.job(id=jid)
            tg = job.task_groups[0]
            tg.count = count
            tg.tasks[0].resources.cpu = 100
            tg.networks = [
                NetworkResource(
                    mode="host",
                    reserved_ports=[Port(label="p", value=7070)],
                )
            ]
            return job

        # 6 asks onto 4 nodes: 4 place, 2 fail/block identically
        for server in (seq, bat):
            server.register_job(port_job("exh", 6))
        assert seq.drain_to_idle(30)
        assert bat.drain_to_idle(60)
        assert placements(seq, "exh") == placements(bat, "exh")
        assert len(placements(bat, "exh")) == 4

        # stop the job; the ports free; a new job reuses them
        for server in (seq, bat):
            server.deregister_job("default", "exh")
        assert seq.drain_to_idle(30)
        assert bat.drain_to_idle(60)
        for server in (seq, bat):
            server.register_job(port_job("reuse", 3))
        assert seq.drain_to_idle(30)
        assert bat.drain_to_idle(60)
        assert placements(seq, "reuse") == placements(bat, "reuse")
        assert len(placements(bat, "reuse")) == 3
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_task_level_static_ports_match():
    """Task-level network asks store their offers in
    tasks[*].networks (never shared.ports) — the port index and the
    kernel mask must see them (code-review r4 finding)."""
    from nomad_tpu.structs import NetworkResource, Port

    nodes = make_nodes(6, seed=2)
    seq = Server(num_schedulers=1, seed=19, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=19, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))

        def task_port_job(jid, count):
            job = mock.job(id=jid)
            tg = job.task_groups[0]
            tg.count = count
            tg.tasks[0].resources.cpu = 100
            tg.tasks[0].resources.networks = [
                NetworkResource(
                    mode="host",
                    reserved_ports=[Port(label="t", value=6060)],
                )
            ]
            return job

        # first job occupies 6060 on 3 nodes via TASK-level offers;
        # the second (separate batch) must see those occupations
        for server in (seq, bat):
            server.register_job(task_port_job("tport-a", 3))
        assert seq.drain_to_idle(30)
        assert bat.drain_to_idle(60)
        for server in (seq, bat):
            server.register_job(task_port_job("tport-b", 3))
        assert seq.drain_to_idle(30)
        assert bat.drain_to_idle(60)
        for jid in ("tport-a", "tport-b"):
            assert placements(seq, jid) == placements(bat, jid), jid
        holders = [
            a.node_id
            for jid in ("tport-a", "tport-b")
            for a in bat.store.allocs_by_job("default", jid)
            if not a.terminal_status()
        ]
        assert len(holders) == 6 and len(set(holders)) == 6, holders
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_device_asks_match_sequential():
    """Device asks run the prescored path with chained free-instance
    accounting (ops/batch.py DeviceInputs): GPU jobs place
    bit-identically to the sequential scheduler, capacity is consumed
    across chained evals, and exhaustion fails identically."""
    from nomad_tpu.structs import RequestedDevice

    nodes = make_nodes(8, seed=6)
    gpu_nodes = [mock.nvidia_node() for _ in range(3)]  # 4 GPUs each
    seq = Server(num_schedulers=1, seed=55, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=55, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes + gpu_nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))

        def gpu_job(jid, count, gpus):
            job = mock.job(id=jid)
            tg = job.task_groups[0]
            tg.count = count
            tg.tasks[0].resources.cpu = 100
            tg.tasks[0].resources.devices = [
                RequestedDevice(name="gpu", count=gpus)
            ]
            return job

        # 3 jobs x 2 instances x 2 GPUs each = 12 GPUs = exactly the
        # cluster's capacity; a 4th job must fail/block
        jobs = [gpu_job(f"gpu-{i}", 2, 2) for i in range(3)]
        jobs.append(gpu_job("gpu-over", 1, 2))
        plain = mock.job(id="gpu-plain")
        plain.task_groups[0].count = 2
        jobs.append(plain)
        for job in jobs:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(30)
        for job in jobs:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(60)

        for job in jobs:
            assert placements(seq, job.id) == placements(
                bat, job.id
            ), f"divergence for {job.id}"
        # every GPU alloc landed on a GPU node, never more than
        # capacity per node
        gpu_ids = {n.id for n in gpu_nodes}
        per_node: dict = {}
        for i in range(3):
            for a in bat.store.allocs_by_job(
                "default", f"gpu-{i}"
            ):
                if a.terminal_status():
                    continue
                assert a.node_id in gpu_ids
                per_node[a.node_id] = per_node.get(
                    a.node_id, 0
                ) + 2
        assert all(v <= 4 for v in per_node.values()), per_node
        worker = bat.workers[0]
        assert worker.prescored >= 3, (
            worker.prescored, worker.fallbacks, worker.errors,
        )
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_all_bad_scores_replay_original_order():
    """When EVERY feasible node scores below the skip threshold (e.g.
    heavy anti-affinity on a small feasible set), the oracle's
    LimitIterator exhausts the source inside the first skip loop and
    replays the diverted nodes in ORIGINAL order — the two-diverted
    reversal quirk applies only when a good emission preceded the
    replay (select.py next()).  Regression for the walk divergence
    found via device asks (kernel picked B where the oracle
    alternates A/B)."""
    import numpy as np

    from nomad_tpu.ops.batch import (
        ChainInputs,
        chained_plan_picks_cols,
    )

    C, E, P, T = 6, 1, 4, 1
    cpu_total = np.full(C, 4000.0)
    mem_total = np.full(C, 8192.0)
    disk_total = np.full(C, 100000.0)
    used_cpu = np.zeros(C)
    used_mem = np.zeros(C)
    used_disk = np.zeros(C)
    used_cpu[[0, 4]] = 100.0
    used_mem[[0, 4]] = 256.0
    feas = np.zeros((E, T, C), bool)
    feas[0, 0, [0, 4]] = True
    stacked = ChainInputs(
        feasible=feas,
        perm=np.arange(C, dtype=np.int32)[None, :],
        ask_cpu=np.full((E, P), 100.0),
        ask_mem=np.full((E, P), 256.0),
        ask_disk=np.full((E, P), 300.0),
        desired_count=np.full((E, P), 4, np.int32),
        limit=np.full((E, P), 3, np.int32),
        distinct_hosts=np.zeros(E, bool),
        tg_idx=np.zeros((E, P), np.int32),
    )
    rows = np.asarray(
        chained_plan_picks_cols(
            cpu_total, mem_total, disk_total,
            used_cpu, used_mem, used_disk,
            stacked, np.full(E, C, np.int32), P,
            wanted=np.full(E, 4, np.int32),
        )[0]
    )
    # picks 2/3: both nodes carry one collision (anti-penalty pushes
    # both below the threshold); the walk must emit them in ORIGINAL
    # shuffle order, alternating exactly like the sequential path
    assert rows[0].tolist() == [0, 4, 0, 4], rows[0]


def test_batch_worker_exports_pipeline_metrics():
    """BatchWorker exports prescored/fallback/mesh-used counters and
    eval-latency percentiles via /v1/metrics (VERDICT r3 weak #7: the
    north-star latency metric must be visible to an operator, not just
    the bench)."""
    import json
    import urllib.request

    from nomad_tpu.api import start_http_server

    bat = Server(num_schedulers=1, seed=9, batch_pipeline=True)
    bat.start()
    http = start_http_server(bat, port=0)
    try:
        for node in make_nodes(8, seed=1):
            bat.register_node(node)
        for job in make_jobs(6, seed=2):
            bat.register_job(job)
        assert bat.drain_to_idle(30)
        base = f"http://127.0.0.1:{http.port}"
        with urllib.request.urlopen(
            base + "/v1/metrics", timeout=10
        ) as resp:
            dump = json.loads(resp.read())
        counters = dump["counters"]
        assert counters.get("batch_worker.prescored", 0) > 0, (
            counters
        )
        # fallback/mesh counters exist (possibly zero on this stream)
        lat = dump["samples"].get("batch_worker.eval_latency_ms")
        assert lat is not None and lat["count"] > 0, dump["samples"]
        assert "p50" in lat and "p99" in lat
        assert lat["p99"] >= lat["p50"] > 0.0
        # prometheus rendering carries the quantiles too
        with urllib.request.urlopen(
            base + "/v1/metrics?format=prometheus", timeout=10
        ) as resp:
            text = resp.read().decode()
        assert 'batch_worker_eval_latency_ms{quantile="0.99"}' in text
    finally:
        http.stop()
        bat.stop()


def test_adaptive_batch_cap_tracks_latency_and_backlog():
    """The adaptive gulp size closes the loop from measured launch/
    replay latency: small batches when keeping up and the full-batch
    estimate blows the budget, full batches under saturation (VERDICT
    r3 #2)."""
    bat = Server(num_schedulers=1, seed=1, batch_pipeline=True)
    try:
        worker = bat.workers[0]
        # keeping up + fast launches: a full batch of 8-wide chunk
        # launches fits the budget
        worker._launch_ewma = {2: 10.0, 4: 12.0, 8: 20.0}
        worker._replay_ewma_ms = 1.0
        assert worker._adaptive_cap() == worker.batch_max

        # keeping up + slow launches: the full batch's chunk chain
        # blows the budget, one wide chunk still fits -> cap 8
        worker._launch_ewma = {2: 30.0, 4: 35.0, 8: 40.0}
        worker._replay_ewma_ms = 5.0
        assert worker._adaptive_cap() == 8

        # launches so slow even one widest chunk misses the budget:
        # the ladder lets the cap narrow to a 4-eval gulp (the old
        # {8, batch_max} candidate set bottomed out at 8)
        worker._launch_ewma = {2: 60.0, 4: 90.0, 8: 260.0}
        worker._replay_ewma_ms = 5.0
        assert worker._adaptive_cap() == 4

        # saturation: backlog >= a full batch -> throughput wins
        class _Broker:
            def ready_count(self, schedulers):
                return worker.batch_max + 5

        real = bat.broker
        bat.broker = _Broker()
        try:
            assert worker._adaptive_cap() == worker.batch_max
        finally:
            bat.broker = real

        # explicit opt-out
        worker.latency_budget_ms = 0.0
        worker._launch_ewma = {2: 9999.0, 4: 9999.0, 8: 9999.0}
        assert worker._adaptive_cap() == worker.batch_max
    finally:
        bat.stop()


def test_adaptive_cap_respects_operator_ceiling(monkeypatch):
    """With NOMAD_TPU_BATCH_MAX below the widest chunk bucket, the
    adaptive cap (and the chunk ladder itself) must never exceed the
    operator's ceiling, and the measured chunk-cost EWMAs still drive
    the decision for non-default ceilings (code-review r4
    findings)."""
    monkeypatch.setenv("NOMAD_TPU_BATCH_MAX", "4")
    bat = Server(num_schedulers=1, seed=1, batch_pipeline=True)
    try:
        worker = bat.workers[0]
        assert worker.batch_max == 4
        assert worker._chunk_buckets() == (2, 4)
        worker._launch_ewma = {2: 10.0, 4: 10.0}
        worker._replay_ewma_ms = 1.0
        assert worker._adaptive_cap() <= 4
    finally:
        bat.stop()
    monkeypatch.setenv("NOMAD_TPU_BATCH_MAX", "32")
    bat = Server(num_schedulers=1, seed=1, batch_pipeline=True)
    try:
        worker = bat.workers[0]
        # a widest-bucket launch too slow for the budget narrows the
        # chunk width AND the gulp: with an unmeasured narrow bucket
        # (seeded at the 50 ms default) only a 4-eval gulp fits
        worker._launch_ewma = {8: 400.0}
        worker._replay_ewma_ms = 5.0
        assert worker._adaptive_cap() == 4
    finally:
        bat.stop()


def test_batch_pipeline_device_affinities_match_sequential():
    """Device AFFINITIES run the prescored path (r5): the allocator's
    matched-weight fraction (reference rank.go:443-461) becomes a
    static per-node kernel score column, exact because the chain gates
    guarantee at most one matching group per node.  Jobs preferring
    big-memory GPUs place bit-identically to the sequential scheduler
    WITHOUT falling back."""
    from nomad_tpu.structs import Affinity, NodeDeviceResource, RequestedDevice

    nodes = make_nodes(6, seed=9)
    big = [mock.nvidia_node() for _ in range(2)]  # memory=11169
    small = []
    for _ in range(2):
        n = mock.node()
        n.node_resources.devices = [
            NodeDeviceResource(
                vendor="nvidia",
                type="gpu",
                name="2070",
                instance_ids=[mock.new_id() for _ in range(4)],
                attributes={"memory": "8000"},
            )
        ]
        n.computed_class = compute_node_class(n)
        small.append(n)

    seq = Server(num_schedulers=1, seed=77, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=77, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes + big + small:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))

        def aff_job(jid, count, weight):
            job = mock.job(id=jid)
            tg = job.task_groups[0]
            tg.count = count
            tg.tasks[0].resources.cpu = 100
            tg.tasks[0].resources.devices = [
                RequestedDevice(
                    name="gpu",
                    count=1,
                    affinities=[
                        Affinity(
                            ltarget="${device.attr.memory}",
                            rtarget="10000",
                            operand=">=",
                            weight=weight,
                        )
                    ],
                )
            ]
            return job

        jobs = [
            aff_job("gaff-pos", 3, 75),   # prefers 11169-memory nodes
            aff_job("gaff-neg", 2, -40),  # avoids them
            aff_job("gaff-more", 4, 75),  # spills after big fills
        ]
        for job in jobs:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(30)
        for job in jobs:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(60)

        for job in jobs:
            assert placements(seq, job.id) == placements(
                bat, job.id
            ), f"divergence for {job.id}"
        # sanity: every alloc landed on a GPU-bearing node and the
        # big-memory nodes got at least one positive-affinity pick
        # (the affinity is soft — binpack + anti-affinity + the
        # unlifted walk limit legitimately spread the rest)
        gpu_ids = {n.id for n in big + small}
        placed = [
            a.node_id
            for a in bat.store.allocs_by_job("default", "gaff-pos")
            if not a.terminal_status()
        ]
        assert placed and set(placed) <= gpu_ids, placed
        assert set(placed) & {n.id for n in big}, placed
        worker = bat.workers[0]
        assert worker.prescored >= 3, (
            worker.prescored, worker.fallbacks, worker.errors,
        )
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_preemption_retry_matches_sequential():
    """Preemption retries run from the prescored path (r5): when a
    prescored pick fails and preemption is enabled, PrescoredStack
    seeds the inner oracle with the recorded shuffle order and the
    kernel's walk-offset (pulls) and hands the eval's remainder to it
    — placements AND preempted-alloc sets must match the sequential
    scheduler bit for bit, without a full-eval fallback."""
    from nomad_tpu.structs import (
        PreemptionConfig,
        SchedulerConfiguration,
    )

    def small_node():
        n = mock.node()
        n.node_resources.cpu = 2000
        n.node_resources.memory_mb = 2048
        n.computed_class = compute_node_class(n)
        return n

    nodes = [small_node() for _ in range(6)]
    seq = Server(num_schedulers=1, seed=91, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=91, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for server in (seq, bat):
            for node in nodes:
                server.register_node(copy.deepcopy(node))
            server.store.set_scheduler_config(
                SchedulerConfiguration(
                    preemption_config=PreemptionConfig(
                        service_scheduler_enabled=True
                    )
                )
            )

        # fill the whole fleet with low-priority occupants
        low = mock.job(id="occ")
        low.priority = 20
        low.task_groups[0].count = 6
        low.task_groups[0].tasks[0].resources.cpu = 1500
        low.task_groups[0].tasks[0].resources.memory_mb = 1200
        # then a high-priority job that can only place by preempting
        high = mock.job(id="vip")
        high.priority = 80
        high.task_groups[0].count = 2
        high.task_groups[0].tasks[0].resources.cpu = 1200
        high.task_groups[0].tasks[0].resources.memory_mb = 1000

        for server in (seq, bat):
            server.register_job(copy.deepcopy(low))
            assert server.drain_to_idle(30)
            server.register_job(copy.deepcopy(high))
            assert server.drain_to_idle(30)

        assert placements(seq, "vip") == placements(bat, "vip")
        assert len(placements(seq, "vip")) == 2

        def preempted(server):
            return sorted(
                a.name
                for a in server.store.allocs_by_job("default", "occ")
                if a.desired_status == "evict"
            )

        assert preempted(seq) == preempted(bat)
        assert preempted(bat)  # something actually got preempted

        worker = bat.workers[0]
        # the vip eval went through the prescored path and the
        # preemption PASSTHROUGH engaged (not a full-eval fallback)
        assert worker.prescored >= 2, (
            worker.prescored, worker.fallbacks, worker.errors,
        )
        assert worker.preempt_passthroughs >= 1
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_preemption_mid_eval_offset():
    """The passthrough seeds the oracle's rotating walk offset from
    the kernel's pulls: picks that SUCCEED before the failing one
    advance the walk, so the preempt retry (and later picks) must
    start from the same rotation as the sequential run.  One node is
    left free so pick 1 places normally and pick 2+ preempt."""
    from nomad_tpu.structs import (
        PreemptionConfig,
        SchedulerConfiguration,
    )

    def small_node():
        n = mock.node()
        n.node_resources.cpu = 2000
        n.node_resources.memory_mb = 2048
        n.computed_class = compute_node_class(n)
        return n

    nodes = [small_node() for _ in range(8)]
    seq = Server(num_schedulers=1, seed=23, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=23, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for server in (seq, bat):
            for node in nodes:
                server.register_node(copy.deepcopy(node))
            server.store.set_scheduler_config(
                SchedulerConfiguration(
                    preemption_config=PreemptionConfig(
                        service_scheduler_enabled=True,
                        batch_scheduler_enabled=True,
                    )
                )
            )

        # occupants on 7 of 8 nodes (count=7 < fleet): one node stays
        # free for the vip's first pick
        low = mock.job(id="occ2")
        low.priority = 10
        low.task_groups[0].count = 7
        low.task_groups[0].tasks[0].resources.cpu = 1500
        low.task_groups[0].tasks[0].resources.memory_mb = 1200
        vip = mock.job(id="vip2")
        vip.priority = 90
        vip.task_groups[0].count = 3
        vip.task_groups[0].tasks[0].resources.cpu = 1200
        vip.task_groups[0].tasks[0].resources.memory_mb = 900

        for server in (seq, bat):
            server.register_job(copy.deepcopy(low))
            assert server.drain_to_idle(30)
            server.register_job(copy.deepcopy(vip))
            assert server.drain_to_idle(30)

        assert placements(seq, "vip2") == placements(bat, "vip2")
        assert len(placements(seq, "vip2")) == 3

        def preempted(server):
            return sorted(
                a.name
                for a in server.store.allocs_by_job(
                    "default", "occ2"
                )
                if a.desired_status == "evict"
            )

        assert preempted(seq) == preempted(bat)
        assert preempted(bat)
        assert bat.workers[0].preempt_passthroughs >= 1
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_mixed_group_device_affinity():
    """A multi-task-group job where only ONE group's device ask has
    affinities must still prescore (regression: stacking [col, None]
    raised and demoted the whole flush to the sequential path)."""
    from nomad_tpu.structs import Affinity, RequestedDevice, TaskGroup, Task, Resources

    nodes = make_nodes(4, seed=3)
    gpus = [mock.nvidia_node() for _ in range(2)]
    seq = Server(num_schedulers=1, seed=41, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=41, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes + gpus:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        job = mock.job(id="mixed-aff")
        g1 = job.task_groups[0]
        g1.count = 2
        g1.tasks[0].resources.cpu = 100
        g1.tasks[0].resources.devices = [
            RequestedDevice(
                name="gpu",
                count=1,
                affinities=[
                    Affinity(
                        ltarget="${device.attr.memory}",
                        rtarget="10000",
                        operand=">=",
                        weight=60,
                    )
                ],
            )
        ]
        job.task_groups.append(
            TaskGroup(
                name="plain",
                count=2,
                tasks=[
                    Task(
                        name="p",
                        driver="mock_driver",
                        resources=Resources(cpu=100, memory_mb=64),
                    )
                ],
            )
        )
        seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(30)
        bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(30)
        assert placements(seq, "mixed-aff") == placements(
            bat, "mixed-aff"
        )
        assert len(placements(bat, "mixed-aff")) == 4
        worker = bat.workers[0]
        assert worker.errors == 0, (
            worker.prescored, worker.fallbacks, worker.errors,
        )
        assert worker.prescored >= 1
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_multi_tg_distinct_hosts():
    """Multi-task-group jobs WITH distinct_hosts run the prescored
    path (r5): the job-wide occupancy = per-group collision carries +
    an occ_extra column for groups placing nothing this eval.  The
    second eval (scaling ONE group) must see the other group's
    existing allocs as occupied nodes, bit-identically to the
    sequential scheduler."""
    from nomad_tpu.structs import (
        CONSTRAINT_DISTINCT_HOSTS,
        Constraint,
        Resources,
        Task,
        TaskGroup,
    )

    nodes = make_nodes(10, seed=5)
    seq = Server(num_schedulers=1, seed=13, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=13, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))

        def dh_job(count_a, count_b):
            job = mock.job(id="dh-multi")
            job.constraints.append(
                Constraint(operand=CONSTRAINT_DISTINCT_HOSTS)
            )
            ga = job.task_groups[0]
            ga.name = "a"
            ga.count = count_a
            ga.tasks[0].resources.cpu = 100
            job.task_groups.append(
                TaskGroup(
                    name="b",
                    count=count_b,
                    tasks=[
                        Task(
                            name="t",
                            driver="mock_driver",
                            resources=Resources(
                                cpu=100, memory_mb=64
                            ),
                        )
                    ],
                )
            )
            return job

        for server in (seq, bat):
            server.register_job(copy.deepcopy(dh_job(3, 3)))
            assert server.drain_to_idle(30)
        assert placements(seq, "dh-multi") == placements(
            bat, "dh-multi"
        )
        assert len(placements(bat, "dh-multi")) == 6
        # scale ONLY group b: group a's allocs have no picks this
        # eval and must still block their nodes (occ_extra)
        for server in (seq, bat):
            job2 = dh_job(3, 6)
            job2.version = 1
            server.register_job(copy.deepcopy(job2))
            assert server.drain_to_idle(30)
        p_seq = placements(seq, "dh-multi")
        p_bat = placements(bat, "dh-multi")
        assert p_seq == p_bat
        assert len(p_bat) == 9
        # distinct_hosts really held: no node carries two allocs
        nodes_used = [n for _name, n in p_bat]
        assert len(nodes_used) == len(set(nodes_used))
        worker = bat.workers[0]
        assert worker.prescored >= 2, (
            worker.prescored, worker.fallbacks, worker.errors,
        )
    finally:
        seq.stop()
        bat.stop()


def test_batch_pipeline_group_level_distinct_hosts():
    """GROUP-level distinct_hosts has per-group semantics (feasible.py
    _satisfies: job AND task collision): group A's picks avoid only
    A's own allocs while group B packs freely — the kernel's dh_tg
    mask must reproduce the sequential scheduler bit for bit, NOT
    job-wide blocking."""
    from nomad_tpu.structs import (
        CONSTRAINT_DISTINCT_HOSTS,
        Constraint,
        Resources,
        Task,
        TaskGroup,
    )

    nodes = make_nodes(4, seed=8)
    seq = Server(num_schedulers=1, seed=19, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=19, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))

        job = mock.job(id="dh-group")
        ga = job.task_groups[0]
        ga.name = "a"
        ga.count = 4  # one per node: group-level distinct
        ga.constraints.append(
            Constraint(operand=CONSTRAINT_DISTINCT_HOSTS)
        )
        ga.tasks[0].resources.cpu = 100
        job.task_groups.append(
            TaskGroup(
                name="b",
                count=6,  # MORE than nodes: must co-locate with a's
                tasks=[
                    Task(
                        name="t",
                        driver="mock_driver",
                        resources=Resources(cpu=100, memory_mb=64),
                    )
                ],
            )
        )
        for server in (seq, bat):
            server.register_job(copy.deepcopy(job))
            assert server.drain_to_idle(30)
        p_seq = placements(seq, "dh-group")
        p_bat = placements(bat, "dh-group")
        assert p_seq == p_bat
        assert len(p_bat) == 10  # 4 + 6: B placed despite A's spread
        # A's allocs really are one-per-node; B co-locates freely
        a_nodes = [n for name, n in p_bat if ".a[" in name]
        assert len(a_nodes) == len(set(a_nodes)) == 4
        worker = bat.workers[0]
        assert worker.prescored >= 1, (
            worker.prescored, worker.fallbacks, worker.errors,
        )
    finally:
        seq.stop()
        bat.stop()


# ---------------------------------------------------------------------------
# pipelined prescore: chunked carry launches + snapshot-delta input cache
# ---------------------------------------------------------------------------


def test_chunked_carry_launches_match_single_launch():
    """Splitting one E-eval chain into PIPELINE_CHUNK-wide launches
    threaded through the kernel's carry output (return_carry=True) is
    bit-identical to the single launch — the invariant the pipelined
    prescore rests on (a lax.scan cut at an eval boundary)."""
    import numpy as np

    from nomad_tpu.ops.batch import (
        ChainInputs,
        chained_plan_picks_cols,
    )

    rng = np.random.default_rng(7)
    C, E, P = 32, 16, 4
    cpu_total = np.full(C, 4000.0)
    mem_total = np.full(C, 8192.0)
    disk_total = np.full(C, 100000.0)
    used = (
        rng.random(C) * 1000,
        rng.random(C) * 2000,
        rng.random(C) * 100,
    )
    stacked = ChainInputs(
        feasible=np.ones((E, 1, C), bool),
        perm=np.stack(
            [rng.permutation(C).astype(np.int32) for _ in range(E)]
        ),
        ask_cpu=np.full((E, P), 100.0),
        ask_mem=np.full((E, P), 256.0),
        ask_disk=np.full((E, P), 300.0),
        desired_count=np.full((E, P), 4, np.int32),
        limit=np.full((E, P), 5, np.int32),
        distinct_hosts=np.zeros(E, bool),
        tg_idx=np.zeros((E, P), np.int32),
    )
    nc = np.full(E, C, np.int32)
    wanted = np.full(E, 4, np.int32)
    r_full, p_full = (
        np.asarray(x)
        for x in chained_plan_picks_cols(
            cpu_total, mem_total, disk_total, *used,
            stacked, nc, P, wanted=wanted,
        )
    )

    def sl(x, a, b):
        return type(x)(*[f[a:b] for f in x])

    carry = None
    rows, pulls = [], []
    for a in range(0, E, 8):
        b = a + 8
        u = used if carry is None else carry[0]
        r, p, carry = chained_plan_picks_cols(
            cpu_total, mem_total, disk_total, u[0], u[1], u[2],
            sl(stacked, a, b), nc[a:b], P, wanted=wanted[a:b],
            return_carry=True,
        )
        rows.append(np.asarray(r))
        pulls.append(np.asarray(p))
    assert (np.concatenate(rows) == r_full).all()
    assert (np.concatenate(pulls) == p_full).all()


def test_pipelined_multi_chunk_gulp_matches_sequential():
    """A burst larger than PIPELINE_CHUNK forces multi-chunk pipelined
    runs (chunk N+1 chains on N's device carry while N-1 replays);
    placements must stay bit-identical to the sequential scheduler."""
    nodes = make_nodes(24, seed=21)
    jobs = make_jobs(20, seed=22)

    seq = Server(num_schedulers=1, seed=55, batch_pipeline=False)
    bat = Server(num_schedulers=1, seed=55, batch_pipeline=True)
    seq.start()
    bat.start()
    try:
        for node in nodes:
            seq.register_node(copy.deepcopy(node))
            bat.register_node(copy.deepcopy(node))
        for job in jobs:
            seq.register_job(copy.deepcopy(job))
        assert seq.drain_to_idle(30)
        # burst-register so the worker drains multi-chunk gulps
        for job in jobs:
            bat.register_job(copy.deepcopy(job))
        assert bat.drain_to_idle(60)
        for job in jobs:
            assert placements(seq, job.id) == placements(
                bat, job.id
            ), f"divergence for {job.id}"
        worker = bat.workers[0]
        assert worker.prescored > 0
        assert worker.timings["assemble"] > 0.0
        # mesh workers (NOMAD_TPU_MESH=1) realize under mesh_fetch
        assert (
            worker.timings["fetch"] > 0.0
            or worker.timings["mesh_fetch"] > 0.0
        )
    finally:
        seq.stop()
        bat.stop()


def test_input_cache_delta_patch_bit_identical():
    """The device-resident usage mirror, delta-patched from the
    store's dirty-row log, must stay bit-identical to from-scratch
    assembly (the live table columns) after a plan commit, a node
    drain, a node register and a driver re-fingerprint."""
    import numpy as np

    bat = Server(num_schedulers=1, seed=31, batch_pipeline=True)
    bat.start()
    try:
        nodes = make_nodes(10, seed=5)
        for node in nodes:
            bat.register_node(node)
        worker = bat.workers[0]
        table = bat.store.node_table

        def assert_mirror_exact(label):
            cols = worker._device_columns(table)
            for got, want in zip(
                cols,
                (
                    table.cpu_total, table.mem_total,
                    table.disk_total, table.cpu_used,
                    table.mem_used, table.disk_used,
                ),
            ):
                np.testing.assert_array_equal(
                    np.asarray(got), want, err_msg=label
                )

        assert_mirror_exact("initial sync")

        # plan commit: usage columns change, topology doesn't -> the
        # dirty-row patch path must reproduce the columns exactly
        for job in make_jobs(3, seed=9):
            bat.register_job(job)
        assert bat.drain_to_idle(30)
        assert_mirror_exact("after plan commit")
        assert worker._input_cache_hits > 0, (
            worker._input_cache_hits, worker._input_cache_misses
        )

        # node drain: topology generation bumps -> full resync
        bat.store.update_node_drain(nodes[0].id, True)
        assert_mirror_exact("after node drain")

        # node register: arena may grow / new row
        extra = make_nodes(1, seed=77)[0]
        bat.register_node(extra)
        assert_mirror_exact("after node register")

        # driver re-fingerprint: re-upsert with changed attributes
        # (totals untouched, but rows could have been reassigned)
        refp = nodes[1]
        refp.attributes = dict(refp.attributes)
        refp.attributes["driver.raw_exec"] = "1"
        bat.store.upsert_node(refp)
        assert_mirror_exact("after driver re-fingerprint")

        # steady state again: another commit after the topo churn
        for job in make_jobs(2, seed=13):
            job.id = job.id + "-post"
            bat.register_job(job)
        assert bat.drain_to_idle(30)
        assert_mirror_exact("after post-churn commit")
    finally:
        bat.stop()


def test_input_cache_hit_rate_exported_on_second_flush():
    """Smoke: two consecutive flushes through the BatchWorker must
    export a batch_worker.input_cache_hit_rate gauge > 0 on /v1/metrics
    after the second flush — the delta cache can't silently stop
    engaging."""
    import json
    import urllib.request

    from nomad_tpu.api import start_http_server

    bat = Server(num_schedulers=1, seed=17, batch_pipeline=True)
    bat.start()
    http = start_http_server(bat, port=0)
    try:
        for node in make_nodes(8, seed=4):
            bat.register_node(node)
        # flush 1: first sync of the device mirror (a miss)
        bat.register_job(make_jobs(1, seed=41)[0])
        assert bat.drain_to_idle(30)
        # flush 2: the plan commit above dirtied rows -> delta patch
        job2 = make_jobs(1, seed=42)[0]
        job2.id = "cache-hit-probe"
        bat.register_job(job2)
        assert bat.drain_to_idle(30)
        worker = bat.workers[0]
        assert worker.prescored >= 2, (
            worker.prescored, worker.fallbacks, worker.errors
        )
        base = f"http://127.0.0.1:{http.port}"
        with urllib.request.urlopen(
            base + "/v1/metrics", timeout=10
        ) as resp:
            dump = json.loads(resp.read())
        # a mesh worker's flushes sync the SHARDED mirror instead;
        # its hit rate is the mesh.mirror_hit_rate gauge
        rate = dump["gauges"].get(
            "batch_worker.input_cache_hit_rate"
        )
        if worker._mesh is not None and not rate:
            rate = dump["gauges"].get("mesh.mirror_hit_rate")
        assert rate is not None, dump["gauges"]
        assert rate > 0.0, dump["gauges"]
    finally:
        http.stop()
        bat.stop()


def test_assembly_caches_are_lru_not_clear_all():
    """A one-off job signature must evict only the coldest cache entry,
    not every warm one (the old clear-all-on-overflow behavior)."""
    from nomad_tpu.server.batch_worker import _LRUCache

    lru = _LRUCache(3)
    for i in range(3):
        lru.put(("gen", i), i)
    # touch entry 0 so it is the warmest
    assert lru.get(("gen", 0)) == 0
    lru.put(("gen", 99), 99)  # one-off: evicts only the coldest (1)
    assert lru.get(("gen", 1)) is None
    assert lru.get(("gen", 0)) == 0
    assert lru.get(("gen", 2)) == 2
    assert lru.get(("gen", 99)) == 99


# ---------------------------------------------------------------------------
# optimistic parallel replay (PR 2)
# ---------------------------------------------------------------------------


def _eval_outcomes(server, job_id):
    """Terminal eval outcomes for a job, order-insensitive (eval ids
    are random per server, so compare the decision-bearing fields)."""
    return sorted(
        (
            e.status,
            e.status_description,
            tuple(sorted(e.queued_allocations.items())),
        )
        for e in server.store.evals_by_job("default", job_id)
    )


def _run_conflict_pair(monkeypatch, strict):
    """Serial-replay vs parallel-replay servers on a tiny cluster
    where every plan in a wave touches nodes an earlier-committed
    plan mutated.  Returns (serial, par, jobs) after both drained."""
    nodes = make_nodes(6, seed=5)
    jobs = []
    for i in range(10):
        job = mock.job(id=f"conflict-{i}")
        job.task_groups[0].count = random.Random(i).randint(2, 3)
        job.task_groups[0].tasks[0].resources.cpu = 300
        jobs.append(job)

    monkeypatch.setenv("NOMAD_TPU_PARALLEL_REPLAY", "0")
    serial = Server(num_schedulers=1, seed=42, batch_pipeline=True)
    monkeypatch.setenv("NOMAD_TPU_PARALLEL_REPLAY", "1")
    if strict:
        monkeypatch.setenv("NOMAD_TPU_REPLAY_STRICT", "1")
    par = Server(num_schedulers=1, seed=42, batch_pipeline=True)
    assert not serial.workers[0].parallel_replay
    assert par.workers[0].parallel_replay
    assert par.workers[0].replay_strict == strict
    serial.start()
    par.start()
    for node in nodes:
        serial.register_node(copy.deepcopy(node))
        par.register_node(copy.deepcopy(node))
    for job in jobs:
        serial.register_job(copy.deepcopy(job))
    assert serial.drain_to_idle(30)
    for job in jobs:
        par.register_job(copy.deepcopy(job))
    assert par.drain_to_idle(30)
    return serial, par, jobs


def test_parallel_replay_bit_identical_under_forced_conflicts(
    monkeypatch,
):
    """The acceptance contract, strict mode: with a tiny cluster
    every plan in a wave touches nodes an earlier-committed plan
    mutated, forcing the conflict ledger to discard speculations and
    re-replay serially — and the committed outcome must stay
    bit-identical to the serial replay loop."""
    serial, par, jobs = _run_conflict_pair(monkeypatch, strict=True)
    try:
        for job in jobs:
            assert placements(serial, job.id) == placements(
                par, job.id
            ), f"divergence for {job.id}"
            assert _eval_outcomes(serial, job.id) == _eval_outcomes(
                par, job.id
            ), f"eval outcome divergence for {job.id}"
        worker = par.workers[0]
        # the forced contention must actually exercise the conflict
        # path (otherwise this test proves nothing)
        assert worker.replay_conflicts > 0
        assert worker.replay_serial_fallbacks > 0
        assert worker.prescored > 0
    finally:
        serial.stop()
        par.stop()


def test_parallel_replay_relaxed_mode_decisions_match_under_contention(
    monkeypatch,
):
    """Default (relaxed) mode on the same contended cluster: own-wave
    plan-node touches are expected (the kernel chain modeled them),
    so speculations commit — and placements plus eval outcomes must
    still match the serial replay loop exactly."""
    serial, par, jobs = _run_conflict_pair(monkeypatch, strict=False)
    try:
        for job in jobs:
            assert placements(serial, job.id) == placements(
                par, job.id
            ), f"divergence for {job.id}"
            assert _eval_outcomes(serial, job.id) == _eval_outcomes(
                par, job.id
            ), f"eval outcome divergence for {job.id}"
        worker = par.workers[0]
        # fresh jobs have no strict nodes, so the relaxed check must
        # actually commit speculations despite the node contention
        assert worker.replay_speculative > 0
    finally:
        serial.stop()
        par.stop()


def test_parallel_replay_commits_speculations_without_conflicts():
    """Disjoint candidate sets (one job per datacenter) commit their
    speculative replays — the fast path must actually engage, with
    zero conflicts, and the counters must be visible on /v1/metrics."""
    server = Server(num_schedulers=1, seed=11, batch_pipeline=True)
    server.start()
    try:
        n_dcs = 6
        for dc in range(n_dcs):
            for node in make_nodes(2, seed=dc):
                node.datacenter = f"dc{dc}"
                node.computed_class = compute_node_class(node)
                server.register_node(node)
        for dc in range(n_dcs):
            job = mock.job(id=f"dc-job-{dc}")
            job.datacenters = [f"dc{dc}"]
            job.task_groups[0].count = 2
            server.register_job(job)
        assert server.drain_to_idle(30)
        worker = server.workers[0]
        for dc in range(n_dcs):
            assert len(placements(server, f"dc-job-{dc}")) == 2
        assert worker.replay_speculative > 0
        assert worker.replay_conflicts == 0
        assert server.metrics.get_counter("replay.speculative") > 0
        assert (
            server.metrics.get_gauge("batch_worker.replay_parallelism")
            >= 1
        )
        assert (
            server.metrics.get_gauge(
                "batch_worker.parallel_replay_enabled"
            )
            == 1.0
        )
    finally:
        server.stop()


def test_parallel_replay_failed_placements_match_serial(monkeypatch):
    """Exhaustion (failed picks -> blocked evals) through the
    speculative wave must produce the same blocked/complete eval
    outcomes as the serial replay loop."""
    nodes = make_nodes(3, seed=2)
    jobs = []
    for i in range(6):
        job = mock.job(id=f"exhaust-{i}")
        job.task_groups[0].count = 4
        job.task_groups[0].tasks[0].resources.cpu = 3000
        jobs.append(job)

    monkeypatch.setenv("NOMAD_TPU_PARALLEL_REPLAY", "0")
    serial = Server(num_schedulers=1, seed=3, batch_pipeline=True)
    monkeypatch.setenv("NOMAD_TPU_PARALLEL_REPLAY", "1")
    par = Server(num_schedulers=1, seed=3, batch_pipeline=True)
    serial.start()
    par.start()
    try:
        for node in nodes:
            serial.register_node(copy.deepcopy(node))
            par.register_node(copy.deepcopy(node))
        for job in jobs:
            serial.register_job(copy.deepcopy(job))
        assert serial.drain_to_idle(30)
        for job in jobs:
            par.register_job(copy.deepcopy(job))
        assert par.drain_to_idle(30)
        for job in jobs:
            assert placements(serial, job.id) == placements(
                par, job.id
            ), f"divergence for {job.id}"
    finally:
        serial.stop()
        par.stop()


def test_adaptive_cap_latency_budget_boundary_and_broker_errors():
    """_adaptive_cap edges: the budget boundary is inclusive (est ==
    budget keeps the big gulp; one tenth of a ms over drops to a
    chunk-sized gulp) and a broker error falls back to the full
    batch."""
    bat = Server(num_schedulers=1, seed=1, batch_pipeline=True)
    try:
        worker = bat.workers[0]
        worker.latency_budget_ms = 250.0
        # keeping up (empty broker): estimated last-eval latency for
        # a 64-eval gulp = 8 launches x the 8-wide chunk cost EWMA
        # + 1 * replay EWMA = 8 * 30.625 + 5 = 250.0 exactly
        worker._replay_ewma_ms = 5.0
        worker._launch_ewma = {2: 10.0, 4: 10.0, 8: 30.625}
        assert worker._adaptive_cap() == worker.batch_max  # est == 250
        worker._launch_ewma = {2: 10.0, 4: 10.0, 8: 30.6375}
        assert worker._adaptive_cap() == 8  # est just over budget

        # a broken broker must not kill sizing: full batch fallback
        class _Exploding:
            def ready_count(self, schedulers):
                raise RuntimeError("broker down")

        real = bat.broker
        bat.broker = _Exploding()
        try:
            worker._launch_ewma = {2: 9999.0, 4: 9999.0, 8: 9999.0}
            assert worker._adaptive_cap() == worker.batch_max
        finally:
            bat.broker = real
    finally:
        bat.stop()


def test_adaptive_cap_inputs_exported_as_gauges():
    """Operators can see WHY _adaptive_cap picked a gulp size: the
    launch EWMA per trace bucket and the replay EWMA are /v1/metrics
    gauges (satellite of PR 2)."""
    server = Server(num_schedulers=1, seed=4, batch_pipeline=True)
    server.start()
    try:
        for node in make_nodes(8, seed=1):
            server.register_node(node)
        for job in make_jobs(4, seed=2):
            server.register_job(job)
        assert server.drain_to_idle(30)
        gauges = server.metrics.dump()["gauges"]
        assert "batch_worker.replay_ewma_ms" in gauges
        # chunk buckets export as .e<width>, mesh buckets as .m<width>
        assert any(
            k.startswith("batch_worker.launch_ewma_ms.e")
            or k.startswith("batch_worker.launch_ewma_ms.m")
            for k in gauges
        ), gauges
    finally:
        server.stop()


def test_deq_ts_is_bounded_and_popped_on_nack():
    """The dequeue-timestamp map must not leak: nacked evals pop their
    stamp, and the map sheds oldest-first past DEQ_TS_MAX even when
    evals vanish without an ack or nack."""
    from nomad_tpu.server.batch_worker import DEQ_TS_MAX
    from nomad_tpu.structs import Evaluation

    server = Server(num_schedulers=1, seed=6, batch_pipeline=True)
    try:
        worker = server.workers[0]
        for i in range(DEQ_TS_MAX + 100):
            worker._note_dequeue(Evaluation(id=f"ev-{i}"))
        assert len(worker._deq_ts) <= DEQ_TS_MAX
        # oldest were shed first
        assert "ev-0" not in worker._deq_ts
        ev = Evaluation(id="nacked")
        worker._note_dequeue(ev)
        worker._nack_quietly(ev, "tok")  # unknown token: still pops
        assert "nacked" not in worker._deq_ts
    finally:
        server.stop()
