"""Client runtime subsystems: allocdir layout, taskenv interpolation,
logmon rotation, alloc GC, heartbeat-stop, previous-alloc watcher
(reference client/allocdir, client/taskenv, client/logmon, client/gc.go,
client/heartbeatstop.go, client/allocwatcher).
"""
import os
import time

from nomad_tpu import mock
from nomad_tpu.client.allocdir import AllocDir, find_alloc_dir
from nomad_tpu.client.allocwatcher import (
    NoopPrevAlloc,
    PrevAllocWatcher,
    watcher_for_alloc,
)
from nomad_tpu.client.gc import AllocGarbageCollector
from nomad_tpu.client.heartbeatstop import HeartbeatStopper
from nomad_tpu.client.logmon import FileRotator, LogMon, read_task_log
from nomad_tpu.client.taskenv import Builder
from nomad_tpu.structs import Node


def wait_until(cond, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# allocdir
# ---------------------------------------------------------------------------


def test_allocdir_layout(tmp_path):
    ad = AllocDir(str(tmp_path), "alloc1")
    td = ad.new_task_dir("web")
    ad.build()
    assert os.path.isdir(ad.data_dir)
    assert os.path.isdir(ad.log_dir)
    assert os.path.isdir(td.local_dir)
    assert os.path.isdir(td.secrets_dir)
    assert td.shared_alloc_dir == ad.shared_dir

    with open(os.path.join(td.local_dir, "f.txt"), "w") as f:
        f.write("x" * 100)
    assert ad.disk_usage_bytes() >= 100
    assert any("web/local/f.txt" in p for p in ad.list_files())

    ad.destroy()
    assert not os.path.isdir(ad.alloc_dir)


def test_allocdir_move_from_migrates_sticky_dirs(tmp_path):
    prev = AllocDir(str(tmp_path), "prev")
    prev.new_task_dir("web")
    prev.build()
    with open(os.path.join(prev.data_dir, "db.sqlite"), "w") as f:
        f.write("data")
    with open(
        os.path.join(prev.task_dirs["web"].local_dir, "cache"), "w"
    ) as f:
        f.write("c")

    nxt = AllocDir(str(tmp_path), "next")
    nxt.new_task_dir("web")
    nxt.move_from(prev)
    assert os.path.exists(os.path.join(nxt.data_dir, "db.sqlite"))
    assert os.path.exists(
        os.path.join(nxt.task_dirs["web"].local_dir, "cache")
    )


def test_find_alloc_dir_reopens(tmp_path):
    ad = AllocDir(str(tmp_path), "a1")
    ad.new_task_dir("web")
    ad.build()
    reopened = find_alloc_dir(str(tmp_path), "a1")
    assert reopened is not None
    assert "web" in reopened.task_dirs
    assert find_alloc_dir(str(tmp_path), "missing") is None


# ---------------------------------------------------------------------------
# taskenv
# ---------------------------------------------------------------------------


def _env_fixture(tmp_path):
    job = mock.job()
    alloc = mock.alloc(job=job)
    tg = job.task_groups[0]
    task = tg.tasks[0]
    task.meta["owner"] = "team-a"
    node = Node(name="n1", datacenter="dc2")
    node.attributes["kernel.name"] = "linux"
    node.meta["rack"] = "r7"
    ad = AllocDir(str(tmp_path), alloc.id)
    td = ad.new_task_dir(task.name)
    b = (
        Builder()
        .set_alloc(alloc, job, tg)
        .set_node(node, region="global")
        .set_task(task, td)
        .set_ports({"http": 8080}, ip="10.0.0.5")
    )
    return b.build(), alloc, job, task, td


def test_taskenv_nomad_vars(tmp_path):
    env, alloc, job, task, td = _env_fixture(tmp_path)
    vals = env.all()
    assert vals["NOMAD_ALLOC_ID"] == alloc.id
    assert vals["NOMAD_JOB_ID"] == job.id
    assert vals["NOMAD_TASK_NAME"] == task.name
    assert vals["NOMAD_TASK_DIR"] == td.local_dir
    assert vals["NOMAD_SECRETS_DIR"] == td.secrets_dir
    assert vals["NOMAD_DC"] == "dc2"
    assert vals["NOMAD_META_owner"] == "team-a"
    assert vals["NOMAD_META_OWNER"] == "team-a"
    assert vals["NOMAD_ADDR_http"] == "10.0.0.5:8080"
    assert vals["NOMAD_PORT_http"] == "8080"
    assert vals["NOMAD_CPU_LIMIT"] == str(task.resources.cpu)


def test_taskenv_interpolation(tmp_path):
    env, alloc, _job, _task, _td = _env_fixture(tmp_path)
    s = env.replace(
        "id=${NOMAD_ALLOC_ID} dc=${node.datacenter} "
        "k=${attr.kernel.name} rack=${meta.rack} none=${meta.nope}"
    )
    assert s == f"id={alloc.id} dc=dc2 k=linux rack=r7 none="
    cfg = env.replace_all(
        {"args": ["--port", "${NOMAD_PORT_http}"], "n": 3}
    )
    assert cfg["args"] == ["--port", "8080"]
    assert cfg["n"] == 3


# ---------------------------------------------------------------------------
# logmon
# ---------------------------------------------------------------------------


def test_file_rotator_rotates_and_prunes(tmp_path):
    rot = FileRotator(
        str(tmp_path), "web.stdout", max_files=3, max_file_size_bytes=10
    )
    for _ in range(10):
        rot.write(b"0123456789")  # exactly one file each
    rot.close()
    files = rot.existing_files()
    assert len(files) <= 3
    # newest data survives
    data = read_task_log(str(tmp_path), "web", "stdout", max_bytes=1000)
    assert data.endswith(b"0123456789")


def test_logmon_pumps_streams(tmp_path):
    import io

    lm = LogMon(str(tmp_path), "web", max_file_size_mb=1)
    lm.pump(io.BytesIO(b"hello out\n"), "stdout")
    lm.pump(io.BytesIO(b"hello err\n"), "stderr")
    lm.wait(2.0)
    lm.close()
    assert b"hello out" in read_task_log(str(tmp_path), "web", "stdout")
    assert b"hello err" in read_task_log(str(tmp_path), "web", "stderr")


def test_exec_driver_rotated_logs(tmp_path):
    from nomad_tpu.client.drivers import RawExecDriver
    from nomad_tpu.client.drivers.base import TaskConfig

    d = RawExecDriver()
    logs = tmp_path / "logs"
    cfg = TaskConfig(
        id="t1",
        name="echo",
        config={"command": "/bin/sh", "args": ["-c", "echo rotated"]},
        alloc_dir=str(tmp_path),
        logs_dir=str(logs),
    )
    d.start_task(cfg)
    d.wait_task("t1", timeout=5)
    assert wait_until(
        lambda: b"rotated"
        in read_task_log(str(logs), "echo", "stdout")
    )


# ---------------------------------------------------------------------------
# gc
# ---------------------------------------------------------------------------


def test_gc_make_room_for_destroys_oldest(tmp_path):
    destroyed = []
    gc = AllocGarbageCollector(
        alloc_base_dir=str(tmp_path),
        max_allocs=3,
        destroy_fn=destroyed.append,
    )
    gc.set_live_count(1)
    gc.mark_terminal("old1")
    gc.mark_terminal("old2")
    # 1 live + 2 terminal = 3; room for 1 more requires evicting 1
    gc.make_room_for(1)
    assert destroyed == ["old1"]
    assert gc.num_marked() == 1


def test_gc_collect_all_and_specific(tmp_path):
    for aid in ("a", "b"):
        os.makedirs(tmp_path / aid)
    gc = AllocGarbageCollector(alloc_base_dir=str(tmp_path))
    gc.mark_terminal("a")
    gc.mark_terminal("b")
    assert gc.collect("a") is True
    assert not os.path.isdir(tmp_path / "a")
    assert gc.collect_all() == 1
    assert not os.path.isdir(tmp_path / "b")
    assert gc.collect("a") is False


# ---------------------------------------------------------------------------
# heartbeatstop
# ---------------------------------------------------------------------------


def test_heartbeatstop_stops_after_disconnect():
    job = mock.job()
    job.task_groups[0].stop_after_client_disconnect_s = 0.1
    alloc = mock.alloc(job=job)

    stopped = []
    hs = HeartbeatStopper(stop_alloc_fn=stopped.append)
    hs.allocation_hook(alloc)
    hs.note_heartbeat_ok()
    assert hs.check_once() == 0  # fresh heartbeat: nothing stops
    time.sleep(0.15)  # no heartbeats arrive
    assert hs.check_once() == 1
    assert stopped == [alloc.id]
    # removed after stopping; doesn't fire twice
    assert hs.check_once() == 0


def test_heartbeatstop_ignores_opted_out_groups():
    alloc = mock.alloc()  # no stop_after_client_disconnect
    hs = HeartbeatStopper(stop_alloc_fn=lambda _x: None)
    hs.allocation_hook(alloc)
    time.sleep(0.05)
    assert hs.expired() == {}


# ---------------------------------------------------------------------------
# allocwatcher
# ---------------------------------------------------------------------------


def test_watcher_noop_without_previous():
    alloc = mock.alloc()
    w = watcher_for_alloc(alloc, {})
    assert isinstance(w, NoopPrevAlloc)
    assert w.wait(0.01) is True


class _FakeRunner:
    def __init__(self):
        self.done = False
        self.alloc_dir_obj = None

    def wait(self, timeout=None):
        return self.done


def test_watcher_local_waits_for_runner(tmp_path):
    prev = _FakeRunner()
    w = PrevAllocWatcher("prev1", prev_runner=prev, migrate=True)
    assert w.wait(0.05) is False
    prev.done = True
    assert w.wait(0.05) is True


def test_watcher_local_migration(tmp_path):
    prev_dir = AllocDir(str(tmp_path), "prev1")
    prev_dir.new_task_dir("web")
    prev_dir.build()
    with open(os.path.join(prev_dir.data_dir, "keep"), "w") as f:
        f.write("1")

    prev = _FakeRunner()
    prev.done = True
    prev.alloc_dir_obj = prev_dir
    w = PrevAllocWatcher(
        "prev1", migrate=True, prev_runner=prev,
        alloc_base_dir=str(tmp_path),
    )
    assert w.wait(1.0) is True
    dest = AllocDir(str(tmp_path), "next1")
    dest.new_task_dir("web")
    assert w.migrate(dest) is True
    assert os.path.exists(os.path.join(dest.data_dir, "keep"))


def test_watcher_remote_polls_server(tmp_path):
    terminal = {"v": False}
    w = PrevAllocWatcher(
        "prev1",
        migrate=True,
        poll_terminal=lambda _aid: terminal["v"],
        poll_interval=0.01,
    )
    assert w.wait(0.05) is False
    terminal["v"] = True
    assert w.wait(1.0) is True
    # remote with no snapshot transport: no data moved
    dest = AllocDir(str(tmp_path), "next1")
    assert w.migrate(dest) is False


def test_watcher_refuses_migration_before_wait(tmp_path):
    prev = _FakeRunner()
    w = PrevAllocWatcher("prev1", migrate=True, prev_runner=prev)
    dest = AllocDir(str(tmp_path), "next1")
    assert w.migrate(dest) is False
