"""CSI volume subsystem tests (reference model:
nomad/state/state_store_test.go CSIVolume cases,
nomad/volumewatcher/volumes_watcher_test.go,
scheduler/feasible_test.go CSIVolumeChecker,
client csi_hook / plugins/csi/fake usage).
"""
import json
import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.api import start_http_server
from nomad_tpu.client.csi import CSIManager, FakeCSIPlugin
from nomad_tpu.server import Server
from nomad_tpu.server.fsm import install_payload, state_payload
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    CSI_ACCESS_MULTI_NODE_MULTI_WRITER,
    CSI_ACCESS_MULTI_NODE_READER,
    CSIVolume,
    VolumeRequest,
)


def wait_until(cond, timeout=10.0, interval=0.03, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    raise AssertionError(f"timeout: {msg or 'condition'}")


def csi_job(vol_id, read_only=False, count=1, **overrides):
    j = mock.job(**overrides)
    j.task_groups[0].count = count
    j.task_groups[0].volumes["data"] = VolumeRequest(
        name="data", type="csi", source=vol_id, read_only=read_only
    )
    return j


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------


def test_volume_register_claims_survive_reregister():
    s = StateStore()
    v = mock.csi_volume()
    s.upsert_csi_volume(v)
    s.claim_csi_volume(v.namespace, v.id, "alloc1", "node1", False)
    v2 = CSIVolume(id=v.id, plugin_id="ebs0", name="renamed")
    s.upsert_csi_volume(v2)
    got = s.csi_volume_by_id("default", v.id)
    assert got.name == "renamed"
    assert got.write_claims == {"alloc1": "node1"}


def test_volume_deregister_blocked_by_claims():
    s = StateStore()
    v = mock.csi_volume()
    s.upsert_csi_volume(v)
    s.claim_csi_volume(v.namespace, v.id, "alloc1", "node1", False)
    with pytest.raises(ValueError):
        s.deregister_csi_volume(v.namespace, v.id)
    s.deregister_csi_volume(v.namespace, v.id, force=True)
    assert s.csi_volume_by_id(v.namespace, v.id) is None


def test_single_node_writer_capacity():
    v = mock.csi_volume()
    assert v.claimable(read_only=False)
    v.claim("a1", "n1", read_only=False)
    assert not v.claimable(read_only=False)
    # multi-writer mode never runs out
    v2 = mock.csi_volume(access_mode=CSI_ACCESS_MULTI_NODE_MULTI_WRITER)
    v2.claim("a1", "n1", read_only=False)
    assert v2.claimable(read_only=False)
    # reader-only mode rejects writers outright
    v3 = mock.csi_volume(access_mode=CSI_ACCESS_MULTI_NODE_READER)
    assert not v3.claimable(read_only=False)
    assert v3.claimable(read_only=True)


def test_csi_plugins_derived_from_nodes():
    s = StateStore()
    n1 = mock.node()
    n1.csi_node_plugins["ebs0"] = True
    n2 = mock.node()
    n2.csi_node_plugins["ebs0"] = False
    s.upsert_node(n1)
    s.upsert_node(n2)
    plugins = s.csi_plugins()
    assert plugins["ebs0"].nodes_expected == 2
    assert plugins["ebs0"].nodes_healthy == 1
    assert plugins["ebs0"].node_ids == [n1.id]


def test_csi_snapshot_roundtrip():
    s = StateStore()
    v = mock.csi_volume()
    s.upsert_csi_volume(v)
    s.claim_csi_volume(v.namespace, v.id, "alloc1", "node1", False)
    fresh = StateStore()
    install_payload(fresh, None, state_payload(s, None))
    got = fresh.csi_volume_by_id(v.namespace, v.id)
    assert got is not None and got.write_claims == {"alloc1": "node1"}


# ---------------------------------------------------------------------------
# scheduling
# ---------------------------------------------------------------------------


@pytest.fixture
def srv():
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=11)
    server.start()
    yield server
    server.stop()


def test_csi_transient_unavailability_divergence_blast_radius():
    """Pins the one documented oracle/TPU divergence (tpu_stack.py
    header): when a node's computed class is memoized task-group
    eligible but the node fails the *transient* CSI availability check
    (unhealthy plugin instance), the oracle aborts the whole walk for
    that pick (reference feasible.go returns nil mid-walk -> the pick
    fails and the eval blocks), while the mask path excludes the node
    and keeps looking.

    Blast radius asserted here:
      * divergence requires the memoized-eligible + transient-failure
        walk order - on seeds where the oracle doesn't trip it, the
        two sides stay bit-identical;
      * when the TPU path places where the oracle blocked, it only
        ever places on nodes that PASS the CSI health check - the
        divergence can yield extra placements, never wrong ones;
      * the TPU path never places FEWER allocs than the oracle (the
        mask only excludes unhealthy nodes; the oracle's abort can
        only lose picks).

    Once the oracle's first mid-walk abort fires, its iterator offset
    drifts from the mask path's for every LATER pick of that eval —
    so subsequent picks may differ in node choice, not just count
    (both remain healthy-only)."""
    from nomad_tpu.sched.generic_sched import ServiceScheduler
    from nomad_tpu.sched.testing import Harness

    diverged = []
    agreed = []
    for seed in range(12):
        results = {}
        for use_tpu in (False, True):
            h = Harness()
            healthy, unhealthy = [], []
            # same computed class: csi plugin health is not part of
            # the class hash (node_class.py), which is exactly what
            # makes the memoized-eligible + unavailable state possible
            for i in range(4):
                n = mock.node()
                n.id = f"csi-node-{i}"  # stable across both runs
                # one unhealthy node: enough walk orders miss it
                # entirely (both sides bit-identical) while others
                # trip the mid-walk abort (documented divergence)
                ok = i % 4 != 3
                n.csi_node_plugins["ebs0"] = ok
                (healthy if ok else unhealthy).append(n.id)
                h.store.upsert_node(n)
            vol = mock.csi_volume(
                plugin_id="ebs0",
                access_mode=CSI_ACCESS_MULTI_NODE_MULTI_WRITER,
            )
            h.store.upsert_csi_volume(vol)
            j = csi_job(vol.id, count=3, id="div")
            h.store.upsert_job(j)
            ev = mock.evaluation(job_id=j.id)
            h.reject_plan = True
            h.process(ServiceScheduler, ev, use_tpu=use_tpu, seed=seed)
            placements = sorted(
                (a.name, a.node_id)
                for plan in h.plans[-1:]  # no plan when every pick blocked
                for v in plan.node_allocation.values()
                for a in v
            )
            results[use_tpu] = (placements, set(healthy))
        oracle, healthy_set = results[False]
        tpu, _ = results[True]
        # the TPU side must never place on a CSI-unhealthy node
        assert all(nid in healthy_set for _, nid in tpu), (seed, tpu)
        assert all(nid in healthy_set for _, nid in oracle), (
            seed,
            oracle,
        )
        if oracle == tpu:
            agreed.append(seed)
        else:
            # divergence shape: the oracle's mid-walk abort lost
            # picks and/or drifted its offset for later picks — the
            # TPU side never places fewer, and both sides stay on
            # healthy nodes (asserted above)
            assert len(tpu) >= len(oracle), (seed, oracle, tpu)
            diverged.append(seed)
    # the scenario must actually exercise the divergence somewhere,
    # and must not diverge universally (it is walk-order dependent)
    assert diverged, "scenario never hit the documented divergence"
    assert agreed, "divergence should be walk-order dependent"


def test_placement_requires_healthy_plugin(srv):
    plugin_nodes = []
    for i in range(2):
        n = mock.node()
        n.csi_node_plugins["ebs0"] = True
        plugin_nodes.append(n.id)
        srv.register_node(n)
    for i in range(2):
        srv.register_node(mock.node())
    vol = mock.csi_volume(
        plugin_id="ebs0",
        access_mode=CSI_ACCESS_MULTI_NODE_MULTI_WRITER,
    )
    srv.store.upsert_csi_volume(vol)

    j = csi_job(vol.id, count=2)
    srv.register_job(j)
    assert srv.drain_to_idle(timeout=10.0)
    allocs = srv.store.allocs_by_job(j.namespace, j.id)
    assert len(allocs) == 2
    assert {a.node_id for a in allocs} <= set(plugin_nodes)
    # the plan applier claimed the volume for the placements
    got = srv.store.csi_volume_by_id(vol.namespace, vol.id)
    assert set(got.write_claims) == {a.id for a in allocs}


def test_plan_apply_rejects_oversubscribed_writer(srv):
    """count=2 on a single-node-writer volume: the applier is the
    claim linearization point — only one placement commits, the other
    is rejected like a node-capacity conflict."""
    for _ in range(2):
        n = mock.node()
        n.csi_node_plugins["ebs0"] = True
        srv.register_node(n)
    vol = mock.csi_volume(plugin_id="ebs0")
    srv.store.upsert_csi_volume(vol)

    j = csi_job(vol.id, count=2)
    srv.register_job(j)
    assert srv.drain_to_idle(timeout=10.0)
    allocs = [
        a
        for a in srv.store.allocs_by_job(j.namespace, j.id)
        if not a.terminal_status()
    ]
    assert len(allocs) == 1
    got = srv.store.csi_volume_by_id(vol.namespace, vol.id)
    assert set(got.write_claims) == {allocs[0].id}


def test_unregistered_volume_blocks_eval(srv):
    n = mock.node()
    n.csi_node_plugins["ebs0"] = True
    srv.register_node(n)
    j = csi_job("nope")
    ev = srv.register_job(j)
    assert srv.drain_to_idle(timeout=10.0)
    assert not srv.store.allocs_by_job(j.namespace, j.id)


def test_write_claim_capacity_blocks_second_writer_until_release(srv):
    n = mock.node()
    n.csi_node_plugins["ebs0"] = True
    srv.register_node(n)
    vol = mock.csi_volume(plugin_id="ebs0")
    srv.store.upsert_csi_volume(vol)

    j1 = csi_job(vol.id, id="writer-1")
    srv.register_job(j1)
    assert srv.drain_to_idle(timeout=10.0)
    assert len(srv.store.allocs_by_job(j1.namespace, j1.id)) == 1

    # single-node-writer is fully claimed: writer-2 can't place
    j2 = csi_job(vol.id, id="writer-2")
    srv.register_job(j2)
    assert srv.drain_to_idle(timeout=10.0)
    assert not srv.store.allocs_by_job(j2.namespace, j2.id)

    # stop writer-1 -> watcher releases the claim -> writer-2 places
    srv.deregister_job(j1.namespace, j1.id)
    wait_until(
        lambda: srv.drain_to_idle(timeout=1.0)
        and len(
            [
                a
                for a in srv.store.allocs_by_job(
                    j2.namespace, j2.id
                )
                if not a.terminal_status()
            ]
        )
        == 1,
        timeout=15.0,
        msg="writer-2 placed after claim release",
    )
    got = srv.store.csi_volume_by_id(vol.namespace, vol.id)
    a2 = [
        a
        for a in srv.store.allocs_by_job(j2.namespace, j2.id)
        if not a.terminal_status()
    ]
    assert set(got.write_claims) == {a2[0].id}


# ---------------------------------------------------------------------------
# client csimanager + fake plugin
# ---------------------------------------------------------------------------


def test_csimanager_mount_unmount(tmp_path):
    plugin = FakeCSIPlugin()
    mgr = CSIManager(data_dir=str(tmp_path), plugins={"ebs0": plugin})
    info = mgr.mount_volume("ebs0", "vol1", "alloc1", False)
    assert plugin.staged["vol1"] == info.staging_path
    assert plugin.published["vol1"] == info.target_path
    # second alloc on same volume: staged once, published twice
    mgr.mount_volume("ebs0", "vol1", "alloc2", True)
    mgr.unmount_volume("vol1", "alloc1")
    # still staged: alloc2 holds it
    assert "vol1" in plugin.staged
    mgr.unmount_volume("vol1", "alloc2")
    assert "vol1" not in plugin.staged
    assert "vol1" not in plugin.published


def test_fingerprint_reports_health():
    healthy = FakeCSIPlugin()
    broken = FakeCSIPlugin(healthy=False)
    mgr = CSIManager(plugins={"ok": healthy, "bad": broken})
    n = mock.node()
    mgr.fingerprint_node(n)
    assert n.csi_node_plugins == {"ok": True, "bad": False}


def test_mount_failure_fails_alloc(tmp_path, srv):
    from nomad_tpu.client.alloc_runner import AllocRunner

    n = mock.node()
    srv.register_node(n)
    vol = mock.csi_volume(plugin_id="ebs0")
    srv.store.upsert_csi_volume(vol)
    j = csi_job(vol.id)
    alloc = mock.alloc(job=j, task_group=j.task_groups[0].name)

    plugin = FakeCSIPlugin(fail_stage=True)
    mgr = CSIManager(data_dir=str(tmp_path), plugins={"ebs0": plugin})
    runner = AllocRunner(
        alloc,
        csi_manager=mgr,
        csi_resolver=lambda ns, vid: srv.store.csi_volume_by_id(ns, vid),
    )
    runner.run()
    assert alloc.client_status == "failed"
    assert not mgr.mounts_for_alloc(alloc.id)


# ---------------------------------------------------------------------------
# HTTP + CLI surface
# ---------------------------------------------------------------------------


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())


def _req(base, path, body=None, method="POST"):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode() if body is not None else None,
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


@pytest.fixture
def api():
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=5)
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    yield server, base
    http.stop()
    server.stop()


def test_csi_http_surface(api):
    server, base = api
    n = mock.node()
    n.csi_node_plugins["ebs0"] = True
    server.register_node(n)

    _req(
        base,
        "/v1/volume/csi/vol-web",
        {"ID": "vol-web", "PluginID": "ebs0", "Name": "web-data"},
        method="PUT",
    )
    vols = _get(base, "/v1/volumes")
    assert len(vols) == 1 and vols[0]["ID"] == "vol-web"

    vol = _get(base, "/v1/volume/csi/vol-web")
    assert vol["PluginID"] == "ebs0"
    assert vol["AccessMode"] == "single-node-writer"

    plugins = _get(base, "/v1/plugins")
    assert plugins[0]["ID"] == "ebs0"
    assert plugins[0]["NodesHealthy"] == 1

    _req(base, "/v1/volume/csi/vol-web", method="DELETE")
    assert _get(base, "/v1/volumes") == []
