"""Tier-1 wiring of tools/check_stage_accounting.py: every key in
``BatchWorker.timings`` must be observed via ``_observe`` and exported
through ``bench.py``'s ``e2e_stage_times_s``, so a new pipeline stage
can't silently vanish from the bench or /v1/metrics."""
import os
import sys

TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
)


def _load():
    sys.path.insert(0, TOOLS)
    try:
        import check_stage_accounting

        return check_stage_accounting
    finally:
        sys.path.remove(TOOLS)


def test_every_stage_is_observed_and_exported():
    lint = _load()
    ok, problems = lint.check()
    assert ok, problems


def test_lint_detects_a_dropped_stage(tmp_path, monkeypatch):
    """The lint actually fires: removing a stage's _observe call (here
    simulated by pointing the lint at a stripped copy) must fail."""
    lint = _load()
    with open(lint.BATCH_WORKER) as fh:
        src = fh.read()
    assert 'self._observe("simulate"' in src
    stripped = src.replace('self._observe("simulate"', '_unused("simulate"')
    bad = tmp_path / "batch_worker.py"
    bad.write_text(stripped)
    monkeypatch.setattr(lint, "BATCH_WORKER", str(bad))
    ok, problems = lint.check()
    assert not ok
    assert any("simulate" in p for p in problems)
