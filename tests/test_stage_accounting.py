"""Tier-1 wiring of tools/check_stage_accounting.py: every key in
``BatchWorker.timings`` must be observed via ``_observe`` and exported
through ``bench.py``'s ``e2e_stage_times_s``, so a new pipeline stage
can't silently vanish from the bench or /v1/metrics."""
import os
import sys

TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
)


def _load():
    sys.path.insert(0, TOOLS)
    try:
        import check_stage_accounting

        return check_stage_accounting
    finally:
        sys.path.remove(TOOLS)


def test_every_stage_is_observed_and_exported():
    lint = _load()
    ok, problems = lint.check()
    assert ok, problems


def test_lint_detects_unregistered_span_name(tmp_path, monkeypatch):
    """The span-registry check actually fires: a span name used in
    batch_worker.py that is missing from trace.SPAN_NAMES (here
    simulated by pointing the lint at a registry copy with one name
    renamed) must fail the lint."""
    lint = _load()
    with open(lint.TRACE_MOD) as fh:
        src = fh.read()
    assert '"batch_worker.simulate"' in src
    stripped = src.replace(
        '"batch_worker.simulate"', '"batch_worker.renamed_simulate"'
    )
    bad = tmp_path / "trace.py"
    bad.write_text(stripped)
    monkeypatch.setattr(lint, "TRACE_MOD", str(bad))
    ok, problems = lint.check()
    assert not ok
    assert any(
        "batch_worker.simulate" in p and "SPAN_NAMES" in p
        for p in problems
    ), problems


def test_span_registry_and_usage_are_parsed():
    """The lint's AST extraction sees real data on the live tree (an
    empty 'used' set would make the registry check vacuous)."""
    lint = _load()
    registry = lint.span_registry(lint._parse(lint.TRACE_MOD))
    used = lint.span_names_used(lint._parse(lint.BATCH_WORKER))
    used |= lint.span_names_used(lint._parse(lint.PLAN_APPLY))
    assert "batch_worker.simulate" in used
    assert "replay.conflict" in used
    assert "plan.apply" in used
    # the chunk-wide stages are emitted via _observe_chunk's f-string
    # name; the lint must still see them as batch_worker.<stage>
    assert "batch_worker.launch" in used
    assert "batch_worker.fetch" in used
    assert used <= registry


def test_lint_detects_a_dropped_stage(tmp_path, monkeypatch):
    """The lint actually fires: removing a stage's _observe call (here
    simulated by pointing the lint at a stripped copy) must fail."""
    lint = _load()
    with open(lint.BATCH_WORKER) as fh:
        src = fh.read()
    assert 'self._observe("simulate"' in src
    stripped = src.replace('self._observe("simulate"', '_unused("simulate"')
    bad = tmp_path / "batch_worker.py"
    bad.write_text(stripped)
    monkeypatch.setattr(lint, "BATCH_WORKER", str(bad))
    ok, problems = lint.check()
    assert not ok
    assert any("simulate" in p for p in problems)
