"""Eval flight recorder tests: tracer unit behavior, the /v1/traces
HTTP surface, the terminal waterfall renderer, and the acceptance
soak — >= 64 evals through the batch pipeline with parallel replay on,
every completed eval carrying a complete well-nested trace
(dequeue -> commit), forced conflicts recording the tripped fence and
the serial re-replay, and tracing overhead staying within budget on a
config2-like run."""
import copy
import json
import random
import time
import urllib.request

from nomad_tpu import mock
from nomad_tpu.server import Server
from nomad_tpu.structs import compute_node_class
from nomad_tpu.trace import MAX_SPANS, SPAN_NAMES, TRACE, Tracer


def make_nodes(n, seed=0, dcs=1, big=False):
    rng = random.Random(seed)
    nodes = []
    for i in range(n):
        node = mock.node()
        if big:
            # roomy nodes: soak streams must place every alloc (the
            # dequeue->commit assertion needs a committed plan)
            node.node_resources.cpu = rng.choice([16000, 32000])
            node.node_resources.memory_mb = rng.choice([32768, 65536])
        else:
            node.node_resources.cpu = rng.choice([4000, 8000])
            node.node_resources.memory_mb = rng.choice([8192, 16384])
        if dcs > 1:
            node.datacenter = f"dc{i % dcs}"
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    return nodes


# -- tracer unit behavior ---------------------------------------------


def test_tracer_records_nested_spans_and_outcome():
    t = Tracer(ring=8)
    t.begin("ev-1", queue="service")
    with t.span("ev-1", "outer"):
        with t.span("ev-1", "inner", detail="x"):
            t.event("ev-1", "mark", n=3)
    t.annotate("ev-1", outcome="speculative")
    t.finish("ev-1", "ack")
    trace = t.get("ev-1")
    assert trace["complete"]
    assert trace["outcome"] == "speculative"
    assert trace["orphans"] == 0
    by_name = {s["name"]: s for s in trace["spans"]}
    assert by_name["broker.dequeue"]["parent"] is None
    assert by_name["outer"]["parent"] is None
    assert by_name["inner"]["parent"] == by_name["outer"]["id"]
    assert by_name["mark"]["parent"] == by_name["inner"]["id"]
    assert by_name["mark"]["dur_ms"] == 0.0
    assert by_name["inner"]["attrs"] == {"detail": "x"}


def test_tracer_nack_and_supersede_override_annotated_outcome():
    """Only a successful ack consumes the annotated outcome: a nack
    or a redelivery supersede describes an attempt that did not
    stick."""
    t = Tracer(ring=8)
    t.begin("ev-n")
    t.annotate("ev-n", outcome="sequential")
    t.finish("ev-n", "nack")
    assert t.get("ev-n")["outcome"] == "nack"

    t.begin("ev-s")
    t.annotate("ev-s", outcome="sequential")
    t.begin("ev-s")  # redelivery supersedes the running attempt
    t.finish("ev-s", "ack")
    outcomes = sorted(
        tr["outcome"]
        for tr in t.recent(limit=10)
        if tr["eval_id"] == "ev-s"
    )
    assert outcomes == ["ack", "superseded"]


def test_tracer_drops_superseded_generations_stale_spans():
    """After a redelivery, the old attempt's in-flight writes resolve
    (by eval id) to the NEW trace; intervals that began before the
    new trace did are the old generation's and must not pollute it
    with negative offsets."""
    t = Tracer(ring=8)
    t.begin("ev-g")
    stale_start = time.monotonic()
    time.sleep(0.002)
    t.begin("ev-g")  # redelivery
    t.add_span("ev-g", "batch_worker.sequential", stale_start, 0.001)
    t.finish("ev-g", "ack")
    trace = t.get("ev-g")
    assert all(s["off_ms"] >= 0.0 for s in trace["spans"]), trace
    assert trace["dropped"] == 1
    assert [s["name"] for s in trace["spans"]] == ["broker.dequeue"]


def test_tracer_ring_is_bounded_and_span_cap_counts_drops():
    t = Tracer(ring=4)
    for i in range(10):
        t.begin(f"ev-{i}")
        t.finish(f"ev-{i}", "ack")
    assert len(t.recent(limit=100)) == 4
    assert t.get("ev-0") is None  # evicted
    assert t.get("ev-9") is not None
    t.begin("ev-big")
    for i in range(MAX_SPANS + 50):
        t.event("ev-big", "mark")
    t.finish("ev-big", "ack")
    trace = t.get("ev-big")
    assert len(trace["spans"]) == MAX_SPANS
    assert trace["dropped"] == 51  # 50 + the broker.dequeue slot

    # redelivery: a second begin supersedes the first trace
    t2 = Tracer(ring=8)
    t2.begin("ev-r")
    t2.begin("ev-r")
    t2.finish("ev-r", "ack")
    superseded = [
        tr
        for tr in t2.recent(limit=10)
        if tr["eval_id"] == "ev-r" and tr["outcome"] == "superseded"
    ]
    assert len(superseded) == 1


def test_tracer_disabled_is_a_noop():
    t = Tracer(ring=8)
    t.set_enabled(False)
    t.begin("ev-off")
    with t.span("ev-off", "outer"):
        t.event("ev-off", "mark")
    t.finish("ev-off", "ack")
    assert t.get("ev-off") is None
    assert t.recent() == []


def test_tracer_recent_filters_slow_and_outcome():
    t = Tracer(ring=16)
    t.begin("ev-fast")
    t.finish("ev-fast", "ack")
    t.begin("ev-slow")
    t.add_span("ev-slow", "work", time.monotonic(), 1.0)  # 1000ms
    t.annotate("ev-slow", outcome="sequential")
    t.finish("ev-slow", "ack")
    slow = t.recent(slow_ms=500.0, limit=10)
    assert [x["eval_id"] for x in slow] == ["ev-slow"]
    seq = t.recent(outcome="sequential", limit=10)
    assert [x["eval_id"] for x in seq] == ["ev-slow"]
    assert t.recent(outcome="nack", limit=10) == []


def test_span_names_in_this_repo_are_registered():
    """Names recorded by the live pipeline must come from the
    documented registry (the lint checks call sites; this checks the
    other direction on a real trace)."""
    t = Tracer(ring=4)
    t.begin("ev-reg")
    t.finish("ev-reg", "ack")
    for span in t.get("ev-reg")["spans"]:
        assert span["name"] in SPAN_NAMES


# -- waterfall renderer -----------------------------------------------


def test_trace_report_renders_waterfall():
    import os
    import sys

    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
        ),
    )
    try:
        import trace_report
    finally:
        sys.path.pop(0)

    t = Tracer(ring=4)
    t.begin("ev-rpt", queue="service")
    with t.span("ev-rpt", "batch_worker.replay", mode="serial"):
        t.event("ev-rpt", "store.commit", index=7)
    t.annotate("ev-rpt", outcome="prescored")
    t.finish("ev-rpt", "ack")
    text = trace_report.render(t.get("ev-rpt"))
    lines = text.splitlines()
    assert "outcome=prescored" in lines[0]
    assert any("batch_worker.replay" in line for line in lines)
    # the nested commit mark is indented under its parent span
    commit = next(line for line in lines if "store.commit" in line)
    assert "  store.commit" in commit
    assert "index=7" in commit
    # listing mode renders summaries without spans
    listing = trace_report.render(t.recent(limit=4))
    assert "ev-rpt" in listing


# -- /v1/traces HTTP surface ------------------------------------------


def _get_json(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())


def test_traces_http_endpoints():
    from nomad_tpu.api import start_http_server

    server = Server(num_schedulers=1, seed=21, batch_pipeline=True)
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    try:
        for node in make_nodes(6, seed=1):
            server.register_node(node)
        evs = []
        for i in range(4):
            job = mock.job(id=f"http-trace-{i}")
            job.task_groups[0].count = 2
            evs.append(server.register_job(job))
        assert server.drain_to_idle(30)

        listing = _get_json(base, "/v1/traces?limit=200")
        listed_ids = {t["eval_id"] for t in listing}
        for ev in evs:
            assert ev.id in listed_ids
        # summaries carry no span bodies; ?full=1 does
        entry = next(t for t in listing if t["eval_id"] == evs[0].id)
        assert isinstance(entry["spans"], int)
        full = _get_json(base, "/v1/traces?limit=200&full=1")
        entry = next(t for t in full if t["eval_id"] == evs[0].id)
        assert isinstance(entry["spans"], list)

        detail = _get_json(base, f"/v1/traces/{evs[0].id}")
        names = [s["name"] for s in detail["spans"]]
        assert "broker.dequeue" in names
        assert "store.commit" in names
        assert detail["complete"]
        # the listing's full trace id (eval#gen) resolves too
        by_tid = _get_json(
            base, f"/v1/traces/{detail['trace_id']}"
        )
        assert by_tid["trace_id"] == detail["trace_id"]

        # filters
        assert _get_json(
            base, "/v1/traces?slow_ms=9000000"
        ) == []
        outcome = detail["outcome"]
        filtered = _get_json(base, f"/v1/traces?outcome={outcome}")
        assert all(t["outcome"] == outcome for t in filtered)
        assert any(t["eval_id"] == evs[0].id for t in filtered)

        # unknown id -> 404
        try:
            urllib.request.urlopen(
                base + "/v1/traces/nope", timeout=10
            )
            assert False, "expected 404"
        except urllib.error.HTTPError as exc:
            assert exc.code == 404

        # metrics exemplars: slow batch_worker samples name the eval
        dump = _get_json(base, "/v1/metrics")
        replay = dump["samples"].get("batch_worker.replay")
        if replay is not None:
            assert any(
                e["trace_id"] in listed_ids
                for e in replay["exemplars"]
            ), replay
    finally:
        http.stop()
        server.stop()


# -- acceptance soak --------------------------------------------------


def _assert_well_nested(trace):
    """Every span's parent exists and encloses it (small epsilon for
    float math); no orphan (never-closed) spans."""
    assert trace["orphans"] == 0, trace
    by_id = {s["id"]: s for s in trace["spans"]}
    eps = 1e-3  # ms
    for span in trace["spans"]:
        assert span["dur_ms"] is not None, span
        parent = span["parent"]
        if parent is None:
            continue
        assert parent in by_id, span
        p = by_id[parent]
        assert span["off_ms"] >= p["off_ms"] - eps, (span, p)
        assert (
            span["off_ms"] + span["dur_ms"]
            <= p["off_ms"] + p["dur_ms"] + eps
        ), (span, p)


def test_soak_64_evals_all_traced_end_to_end():
    """>= 64 evals through the batch pipeline with parallel replay on:
    every completed eval has a complete, well-nested trace spanning
    dequeue -> state commit."""
    server = Server(num_schedulers=1, seed=77, batch_pipeline=True)
    assert server.workers[0].parallel_replay
    server.start()
    try:
        for node in make_nodes(16, seed=9, dcs=4, big=True):
            server.register_node(node)
        evs = []
        for i in range(64):
            job = mock.job(id=f"soak-{i}")
            if i % 3 == 2:
                job.type = "batch"
            job.task_groups[0].count = 2
            job.task_groups[0].tasks[0].resources.cpu = 200
            evs.append(server.register_job(job))
        assert server.drain_to_idle(120)

        # every job placed: exhaustion would legitimately skip the
        # plan commit and void the dequeue->commit assertion below
        for i in range(64):
            placed = [
                a
                for a in server.store.allocs_by_job(
                    "default", f"soak-{i}"
                )
                if not a.terminal_status()
            ]
            assert len(placed) == 2, f"soak-{i} placed {len(placed)}"

        speculated = 0
        for ev in evs:
            trace = TRACE.get(ev.id)
            assert trace is not None, f"no trace for {ev.id}"
            assert trace["complete"], trace
            assert trace["outcome"] not in (None, "nack"), trace
            assert trace["dropped"] == 0
            names = [s["name"] for s in trace["spans"]]
            # dequeue -> commit: the trace covers the whole lifecycle
            assert names[0] == "broker.dequeue", names
            assert "store.commit" in names, (trace["outcome"], names)
            # every eval enters the pipeline through a gulp OR a
            # mid-chain admission (continuous micro-batching)
            assert (
                "batch_worker.gulp" in names
                or "batch_worker.admit" in names
            ), names
            # a timed scheduling stage is present on every path
            assert (
                "batch_worker.replay" in names
                or "replay.commit" in names
                or "batch_worker.sequential" in names
            ), names
            _assert_well_nested(trace)
            if "replay.speculate" in names:
                speculated += 1
                spec = next(
                    s
                    for s in trace["spans"]
                    if s["name"] == "replay.speculate"
                )
                # straggler attribution: the pool thread is recorded
                assert spec["thread"].startswith("replay-spec"), spec
        # the wave path must actually have engaged for the soak to
        # mean anything
        assert speculated > 0
        assert server.workers[0].replay_speculative > 0
    finally:
        server.stop()


def test_forced_conflict_trace_records_fence_and_serial_replay(
    monkeypatch,
):
    """Strict mode on a tiny contended cluster forces conflicts: the
    discarded speculation's trace must record WHICH fence tripped and
    the serial re-replay that followed."""
    monkeypatch.setenv("NOMAD_TPU_REPLAY_STRICT", "1")
    server = Server(num_schedulers=1, seed=42, batch_pipeline=True)
    assert server.workers[0].replay_strict
    server.start()
    try:
        for node in make_nodes(6, seed=5):
            server.register_node(node)
        evs = []
        for i in range(10):
            job = mock.job(id=f"tconflict-{i}")
            job.task_groups[0].count = random.Random(i).randint(2, 3)
            job.task_groups[0].tasks[0].resources.cpu = 300
            evs.append(server.register_job(job))
        assert server.drain_to_idle(60)
        worker = server.workers[0]
        assert worker.replay_conflicts > 0

        conflicted = []
        for ev in evs:
            trace = TRACE.get(ev.id)
            if trace is None:
                continue
            for span in trace["spans"]:
                if span["name"] == "replay.conflict":
                    conflicted.append((trace, span))
        assert conflicted, "no trace recorded a replay.conflict"
        for trace, conflict in conflicted:
            # the tripped fence is named ...
            assert conflict["attrs"].get("fence") in {
                "strict_node",
                "plan_node",
                "job_ledger",
                "job_version",
                "scheduler_config",
                "deployment",
                "readiness",
            }, conflict
            names = [s["name"] for s in trace["spans"]]
            # ... the demotion is marked with its reason ...
            fallback = next(
                s
                for s in trace["spans"]
                if s["name"] == "replay.serial_fallback"
            )
            assert fallback["attrs"]["reason"] == "conflict"
            # ... and the serial re-replay actually ran
            assert (
                "batch_worker.replay" in names
                or "batch_worker.sequential" in names
            ), names
    finally:
        server.stop()


def test_trace_overhead_under_budget_on_config2_like_run():
    """Always-on tracing must cost < 5% wall time on a config2-like
    batch stream.  Interleaved on/off runs, min-of-2 per mode (min
    filters scheduler noise); a small absolute allowance covers timer
    jitter at this miniature scale.  A per-op microbench additionally
    bounds the recorder's primitive cost so the wall-clock contract
    isn't carried by noise alone."""
    # microbench: span open+close and event append, amortized
    t = Tracer(ring=8)
    t.begin("ev-micro")
    n_ops = 20_000
    t0 = time.perf_counter()
    for _ in range(n_ops // 2):
        with t.span("ev-micro", "batch_worker.replay"):
            pass
        t.event("ev-micro", "store.commit", index=1)
    per_op_us = (time.perf_counter() - t0) / n_ops * 1e6
    # ~25 trace ops per eval at ~10ms/eval -> well under 1% even at
    # 20us/op; a regression past this bound would threaten the 5%
    assert per_op_us < 50.0, f"{per_op_us:.1f}us per trace op"

    def run_once(enabled, rep):
        TRACE.set_enabled(enabled)
        server = Server(
            num_schedulers=1, seed=1000 + rep, batch_pipeline=True
        )
        server.start()
        try:
            for node in make_nodes(24, seed=3):
                server.register_node(node)
            jobs = []
            for i in range(24):
                job = mock.job(id=f"ovh-{rep}-{int(enabled)}-{i}")
                job.type = "batch"
                job.task_groups[0].count = 10
                job.task_groups[0].tasks[0].resources.cpu = 100
                jobs.append(job)
            t0 = time.monotonic()
            for job in jobs:
                server.register_job(job)
            assert server.drain_to_idle(120)
            return time.monotonic() - t0
        finally:
            server.stop()

    times = {True: [], False: []}
    try:
        for rep in range(2):
            for enabled in (True, False):
                times[enabled].append(run_once(enabled, rep))
    finally:
        TRACE.set_enabled(True)
    t_on, t_off = min(times[True]), min(times[False])
    overhead_pct = (t_on - t_off) / t_off * 100.0
    # the 5% contract, with a 0.2s absolute allowance: at this
    # miniature scale a sub-0.2s delta is scheduler jitter, not
    # recorder cost (the microbench above pins the per-op cost)
    assert t_on <= t_off * 1.05 + 0.2, (
        f"tracing overhead {overhead_pct:.1f}% "
        f"(on={t_on:.2f}s off={t_off:.2f}s)"
    )
