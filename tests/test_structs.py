"""Unit tests for the data model and resource math
(reference test model: nomad/structs/funcs_test.go, structs_test.go).
"""
import math

from nomad_tpu import mock
from nomad_tpu.structs import (
    Allocation,
    AllocatedResources,
    AllocatedTaskResources,
    AllocatedSharedResources,
    ComparableResources,
    NetworkIndex,
    NetworkResource,
    Port,
    allocs_fit,
    compute_node_class,
    score_fit_binpack,
    score_fit_spread,
)


def util(cpu, mem):
    return ComparableResources(cpu=cpu, memory_mb=mem)


def test_score_fit_binpack_bounds():
    node = mock.node()
    node.reserved_resources.cpu = 0
    node.reserved_resources.memory_mb = 0
    # empty node: free=1.0 both => 20 - 20 = 0
    assert score_fit_binpack(node, util(0, 0)) == 0.0
    # full node: free=0 => 20 - 2 = 18
    full = util(node.node_resources.cpu, node.node_resources.memory_mb)
    assert score_fit_binpack(node, full) == 18.0
    # spread is the inverse shape
    assert score_fit_spread(node, util(0, 0)) == 18.0
    assert score_fit_spread(node, full) == 0.0


def test_score_fit_formula():
    node = mock.node()
    node.reserved_resources.cpu = 0
    node.reserved_resources.memory_mb = 0
    u = util(2000, 4096)
    free_cpu = 1 - 2000 / node.node_resources.cpu
    free_mem = 1 - 4096 / node.node_resources.memory_mb
    # the framework defines the fitness exponential at f32 precision
    # (structs/funcs.py _pow10) so host and accelerator agree
    # bit-for-bit; the raw-f64 reference value is matched to f32 eps
    expected = 20.0 - (10**free_cpu + 10**free_mem)
    assert abs(score_fit_binpack(node, u) - expected) < 1e-6
    import numpy as np

    exact = 20.0 - float(
        np.float32(10.0**free_cpu) + np.float32(10.0**free_mem)
    )
    assert score_fit_binpack(node, u) == exact


def test_allocs_fit_dimensions():
    node = mock.node()
    fits, dim, used = allocs_fit(node, [])
    assert fits
    big = Allocation(
        allocated_resources=AllocatedResources(
            tasks={
                "t": AllocatedTaskResources(cpu=100000, memory_mb=10)
            }
        )
    )
    fits, dim, _ = allocs_fit(node, [big])
    assert not fits and dim == "cpu"


def test_allocs_fit_ignores_terminal():
    node = mock.node()
    dead = mock.alloc(client_status="failed")
    fits, _, used = allocs_fit(node, [dead])
    assert fits and used.cpu == 0


def test_network_index_static_collision():
    node = mock.node()
    idx = NetworkIndex()
    idx.set_node(node)
    ask = NetworkResource(reserved_ports=[Port("http", 8080)])
    offer = idx.assign_ports(ask)
    assert offer is not None and offer[0].value == 8080
    idx.add_reserved_ports(offer)
    # same static port again collides
    assert idx.assign_ports(ask) is None


def test_network_index_dynamic_ports():
    node = mock.node()
    idx = NetworkIndex()
    idx.set_node(node)
    ask = NetworkResource(dynamic_ports=[Port("a"), Port("b")])
    offer = idx.assign_ports(ask)
    assert len(offer) == 2
    assert offer[0].value != offer[1].value


def test_computed_class_stability():
    a = mock.node()
    b = mock.node()
    # names/ids differ but class-relevant state matches
    b.attributes = dict(a.attributes)
    b.meta = dict(a.meta)
    b.datacenter = a.datacenter
    b.node_class = a.node_class
    b.node_resources.devices = a.node_resources.devices
    assert compute_node_class(a) == compute_node_class(b)
    b.attributes = dict(a.attributes, extra="1")
    assert compute_node_class(a) != compute_node_class(b)
    # unique.* keys are excluded
    c = mock.node()
    c.attributes = dict(a.attributes)
    c.meta = dict(a.meta)
    c.datacenter = a.datacenter
    c.attributes["unique.hostname"] = "xyz"
    assert compute_node_class(a) == compute_node_class(c)


def test_alloc_terminal_status():
    a = mock.alloc()
    assert not a.terminal_status()
    a.desired_status = "stop"
    assert a.terminal_status()
    b = mock.alloc(client_status="failed")
    assert b.terminal_status()


def test_alloc_index_parse():
    a = mock.alloc()
    a.name = "job.web[7]"
    assert a.index() == 7
