"""Remote client plumbing units (client/remote.py): the HTTP-backed
server handle + callback endpoint + server-side proxy, driven
in-process against a real Server + HTTP API (the soak covers the
multi-OS-process shape; these cover the seams directly)."""
from __future__ import annotations

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api import start_http_server
from nomad_tpu.client.client import Client
from nomad_tpu.client.remote import RemoteServer
from nomad_tpu.server import Server
from nomad_tpu.structs import Resources, Task


def wait_until(cond, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def remote_world(tmp_path):
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=31)
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    remote = RemoteServer([base])
    client = Client(
        remote,
        node=mock.node(),
        data_dir=str(tmp_path / "cdata"),
        fingerprint=False,
        heartbeat_interval=0.3,
        watch_interval=0.2,
        drivers=["mock_driver", "raw_exec"],
    )
    client.start()
    yield server, client, remote, base
    client.stop()
    remote.stop()
    http.stop()
    server.stop()


def test_remote_client_runs_and_reports(remote_world):
    server, client, _remote, _base = remote_world
    job = mock.batch_job(id="rjob")
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0] = Task(
        name="t", driver="mock_driver", config={"run_for": 0.1}
    )
    server.register_job(job)
    assert server.drain_to_idle(10)
    assert wait_until(
        lambda: any(
            a.client_status == "complete"
            and a.task_states.get("t") is not None
            for a in server.store.allocs_by_job("default", "rjob")
        )
    ), [
        (a.client_status, dict(a.task_states))
        for a in server.store.allocs_by_job("default", "rjob")
    ]


def test_remote_log_read_and_tail_via_proxy(remote_world, tmp_path):
    """`alloc logs` (non-follow) AND the follow cursor both route
    server -> HTTPClientProxy -> client callback -> the client's own
    disk (review r5: read_task_log was missing on Client)."""
    server, client, _remote, _base = remote_world
    job = mock.job(id="ljob")
    tg = job.task_groups[0]
    tg.count = 1
    tg.tasks = [
        Task(
            name="main",
            driver="raw_exec",
            config={
                "command": "/bin/sh",
                "args": ["-c", "echo from-remote; sleep 30"],
            },
            resources=Resources(cpu=100, memory_mb=64),
        )
    ]
    server.register_job(job)
    assert server.drain_to_idle(10)
    alloc = server.store.allocs_by_job("default", "ljob")[0]
    assert wait_until(
        lambda: server.store.alloc_by_id(alloc.id).client_status
        == "running"
    )
    # non-follow read through the server's proxy surface
    assert wait_until(
        lambda: b"from-remote"
        in server.read_task_log(alloc.id, "main", "stdout")
    )
    # follow step through the same proxy
    data, cursor = server.tail_task_log(
        alloc.id, "main", "stdout", None
    )
    assert b"from-remote" in data
    assert cursor is not None
    # exec through the proxy too
    rc, out = server.exec_alloc(alloc.id, "main", ["echo", "hi"])
    assert rc == 0
    assert b"hi" in out


def test_remote_heartbeat_reregisters_after_purge(remote_world):
    """A purged node's next heartbeat 404s; the remote handle maps it
    to KeyError so the client re-registers (review r5: the HTTPError
    leaked past the re-registration contract)."""
    server, client, _remote, _base = remote_world
    node_id = client.node.id
    assert wait_until(
        lambda: server.store.node_by_id(node_id) is not None
    )
    server.purge_node(node_id)
    assert server.store.node_by_id(node_id) is None
    # the heartbeat loop must bring it back without a restart
    assert wait_until(
        lambda: server.store.node_by_id(node_id) is not None,
        timeout=10,
    )
