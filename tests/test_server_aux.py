"""Server auxiliary subsystems: TimeTable, autopilot dead-server
cleanup, node events, multiregion job handling (reference
nomad/timetable.go, nomad/autopilot.go, structs NodeEvent/fsm.go:247,
structs.go Multiregion + deploymentwatcher/multiregion_oss.go).
"""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server
from nomad_tpu.server.autopilot import Autopilot, AutopilotConfig
from nomad_tpu.server.cluster import TestCluster
from nomad_tpu.server.timetable import TimeTable
from nomad_tpu.structs import (
    Multiregion,
    MultiregionRegion,
    MultiregionStrategy,
    Node,
)


def wait_until(pred, timeout=8.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


# ---------------------------------------------------------------------------
# timetable
# ---------------------------------------------------------------------------


def test_timetable_witness_and_lookup():
    tt = TimeTable(granularity_s=1.0, limit_s=100.0)
    tt.witness(10, 1000.0)
    tt.witness(20, 1010.0)
    tt.witness(30, 1020.0)
    assert tt.nearest_index(1015.0) == 20
    assert tt.nearest_index(1020.0) == 30
    assert tt.nearest_index(999.0) == 0
    assert tt.nearest_time(20) == 1010.0
    assert tt.nearest_time(5) == 0.0


def test_timetable_granularity_coalesces():
    tt = TimeTable(granularity_s=60.0)
    tt.witness(1, 1000.0)
    tt.witness(2, 1001.0)  # within granularity: dropped
    assert tt.nearest_index(2000.0) == 1


def test_timetable_retention_rolls_off():
    tt = TimeTable(granularity_s=1.0, limit_s=10.0)
    tt.witness(1, 1000.0)
    tt.witness(2, 1020.0)  # 1000.0 is now past the 10s limit
    assert tt.nearest_index(1005.0) == 0


def test_timetable_roundtrip():
    tt = TimeTable(granularity_s=1.0)
    tt.witness(5, 1000.0)
    tt2 = TimeTable()
    tt2.deserialize(tt.serialize())
    assert tt2.nearest_index(1001.0) == 5


def test_server_witnesses_state_mutations():
    srv = Server()
    srv.start()
    try:
        srv.register_node(mock.node())
        assert srv.timetable.nearest_index(time.time() + 1) > 0
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# node events
# ---------------------------------------------------------------------------


def test_node_events_emitted_on_lifecycle():
    srv = Server(heartbeat_ttl=60.0)
    srv.start()
    try:
        node = mock.node()
        srv.register_node(node)
        stored = srv.store.node_by_id(node.id)
        assert any(
            "registered" in e.message for e in stored.events
        )
        srv.update_node_drain(node.id, True)
        stored = srv.store.node_by_id(node.id)
        assert any(e.subsystem == "Drain" for e in stored.events)
        srv.update_node_status(node.id, "down")
        stored = srv.store.node_by_id(node.id)
        assert any(
            "heartbeat missed" in e.message for e in stored.events
        )
        assert all(e.create_index > 0 for e in stored.events)
    finally:
        srv.stop()


def test_node_event_history_is_bounded():
    from nomad_tpu.structs import MAX_NODE_EVENTS, NodeEvent

    node = Node()
    node.add_event(NodeEvent(message="Node registered"))
    for i in range(25):
        node.add_event(NodeEvent(message=f"e{i}"))
    assert len(node.events) == MAX_NODE_EVENTS
    # the registration event is pinned
    assert node.events[0].message == "Node registered"
    assert node.events[-1].message == "e24"


# ---------------------------------------------------------------------------
# autopilot
# ---------------------------------------------------------------------------


def test_autopilot_prunes_dead_server():
    c = TestCluster(3, heartbeat_ttl=60.0)
    c.start()
    try:
        leader = c.wait_for_leader()
        victim = c.followers()[0]
        # hard-kill: no graceful leave, gossip must detect the failure
        victim.raft.stop()
        victim.gossip.stop()
        for s in c.servers:
            if s.addr != victim.addr:
                c.transport.partition(victim.addr, s.addr)
        wait_until(
            lambda: any(
                m.addr == victim.addr and m.status in ("dead", "left")
                for m in leader.gossip.all_members()
            ),
            timeout=20.0,
            msg="gossip marks victim failed",
        )
        removed = leader.autopilot.prune_dead_servers()
        assert victim.addr in removed
        assert victim.addr not in leader.raft.peers
        # the config change replicates through the log; the other
        # follower drops the peer when it applies the entry
        other = [
            s for s in c.followers() if s.addr != victim.addr
        ][0]
        wait_until(
            lambda: victim.addr not in other.raft.peers,
            timeout=5.0,
            msg="follower applies the replicated config change",
        )
        stats = leader.autopilot.stats()
        assert stats["NumServers"] == 2
    finally:
        c.stop()


def test_autopilot_respects_quorum_guard():
    """With 2 of 3 dead, removal would exceed (n-1)/2: refuse."""

    from types import SimpleNamespace

    class FakeGossip:
        def all_members(self):
            return [
                SimpleNamespace(addr="a", status="alive"),
                SimpleNamespace(addr="b", status="dead"),
                SimpleNamespace(addr="c", status="dead"),
            ]

    class FakeRaft:
        addr = "a"
        peers = ["b", "c"]

    class FakeCluster:
        gossip = FakeGossip()
        raft = FakeRaft()

        def is_leader(self):
            return True

        def broadcast_peer_removal(self, addr):
            raise AssertionError("must not remove")

    ap = Autopilot(FakeCluster())
    assert ap.prune_dead_servers() == []


def test_autopilot_disabled_by_config():
    class FakeCluster:
        def is_leader(self):
            return True

    ap = Autopilot(
        FakeCluster(),
        config=AutopilotConfig(cleanup_dead_servers=False),
    )
    assert ap.prune_dead_servers() == []


def test_autopilot_readds_stably_alive_server():
    """A server pruned by dead-server cleanup that restarts at the
    same address is gossip-alive but absent from the raft config; the
    reconcile pass must re-add it (reference leader.go
    reconcileMember -> addRaftPeer) or it never receives another log
    entry.  Members inside the stabilization window, other regions'
    servers, and non-server roles stay out."""

    from types import SimpleNamespace

    old = time.monotonic() - 60.0

    class FakeGossip:
        def alive_members(self):
            return [
                SimpleNamespace(  # self: already in config
                    addr="a", role="server", region="global",
                    status_time=old,
                ),
                SimpleNamespace(  # the restarted server
                    addr="c", role="server", region="global",
                    status_time=old,
                ),
                SimpleNamespace(  # still inside stabilization
                    addr="d", role="server", region="global",
                    status_time=time.monotonic(),
                ),
                SimpleNamespace(  # federation route, not our raft
                    addr="e", role="server", region="eu",
                    status_time=old,
                ),
            ]

    class FakeRaft:
        addr = "a"
        peers = ["b"]

    class FakeCluster:
        gossip = FakeGossip()
        raft = FakeRaft()
        region = "global"
        added = []

        def is_leader(self):
            return True

        def broadcast_peer_add(self, addr):
            self.added.append(addr)
            return True

    cluster = FakeCluster()
    ap = Autopilot(cluster)
    assert ap.readd_joined_servers() == ["c"]
    assert cluster.added == ["c"]
    assert ap.readded == ["c"]


def test_autopilot_readd_commits_through_raft_log():
    """End-to-end on a real cluster: prune a hard-killed follower,
    heal the partition so its (restarted) gossip refutes the DEAD
    rumor, and the reconcile pass restores it to every member's
    replicated configuration."""
    c = TestCluster(3, heartbeat_ttl=60.0)
    c.start()
    try:
        leader = c.wait_for_leader()
        victim = c.followers()[0]
        victim.raft.stop()
        for s in c.servers:
            if s.addr != victim.addr:
                c.transport.partition(victim.addr, s.addr)
        wait_until(
            lambda: any(
                m.addr == victim.addr and m.status in ("dead", "left")
                for m in leader.gossip.all_members()
            ),
            timeout=20.0,
            msg="gossip marks victim failed",
        )
        # the background autopilot loop may beat the explicit call;
        # assert the effect, not which pass won
        leader.autopilot.prune_dead_servers()
        wait_until(
            lambda: victim.addr not in leader.raft.peers,
            timeout=10.0,
            msg="dead-server cleanup prunes the victim",
        )
        # "restart": heal the partition; the victim's still-running
        # gossip refutes the DEAD rumor exactly like a relaunched
        # process at the same address would
        for s in c.servers:
            if s.addr != victim.addr:
                c.transport.heal(victim.addr, s.addr)
        wait_until(
            lambda: any(
                m.addr == victim.addr and m.status == "alive"
                for m in leader.gossip.all_members()
            ),
            timeout=20.0,
            msg="gossip sees victim alive again",
        )
        # bypass the stabilization wait: the window is operator
        # config, not part of the mechanism under test
        leader.autopilot._default_config.server_stabilization_time_s = 0.0
        leader.autopilot.readd_joined_servers()
        wait_until(
            lambda: victim.addr in leader.raft.peers,
            timeout=15.0,
            msg="reconcile re-adds the restarted server",
        )
        other = [
            s for s in c.followers() if s.addr != victim.addr
        ][0]
        wait_until(
            lambda: victim.addr in other.raft.peers,
            timeout=5.0,
            msg="follower applies the replicated re-add",
        )
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# multiregion
# ---------------------------------------------------------------------------


def test_multiregion_jobspec_parse():
    from nomad_tpu.jobspec import parse

    job = parse(
        """
        job "global-web" {
          datacenters = ["dc1"]
          multiregion {
            strategy {
              max_parallel = 1
              on_failure = "fail_all"
            }
            region "west" {
              count = 2
              datacenters = ["us-west-1"]
            }
            region "east" {
              count = 3
              datacenters = ["us-east-1"]
              meta { tier = "primary" }
            }
          }
          group "web" {
            count = 1
            task "srv" {
              driver = "mock_driver"
            }
          }
        }
        """
    )
    assert job.multiregion is not None
    assert job.multiregion.strategy.max_parallel == 1
    assert job.multiregion.strategy.on_failure == "fail_all"
    assert [r.name for r in job.multiregion.regions] == ["west", "east"]
    east = job.multiregion.region("east")
    assert east.count == 3
    assert east.meta == {"tier": "primary"}


def test_multiregion_register_interpolates_local_region():
    srv = Server()
    srv.region = "east"
    srv.start()
    try:
        node = mock.node(datacenter="us-east-1")
        srv.register_node(node)
        job = mock.job(id="mr")
        job.multiregion = Multiregion(
            strategy=MultiregionStrategy(max_parallel=1),
            regions=[
                MultiregionRegion(
                    name="west", count=1, datacenters=["us-west-1"]
                ),
                MultiregionRegion(
                    name="east", count=2, datacenters=["us-east-1"],
                    meta={"tier": "primary"},
                ),
            ],
        )
        srv.register_job(job)
        stored = srv.store.job_by_id("default", "mr")
        assert stored.region == "east"
        assert stored.datacenters == ["us-east-1"]
        assert stored.meta.get("tier") == "primary"
        assert all(tg.count == 2 for tg in stored.task_groups)
    finally:
        srv.stop()


def test_multiregion_codec_roundtrip():
    from nomad_tpu.api.codec import job_from_dict, job_to_dict

    job = mock.job(id="mr2")
    job.multiregion = Multiregion(
        strategy=MultiregionStrategy(max_parallel=2, on_failure="fail_local"),
        regions=[MultiregionRegion(name="west", count=4)],
    )
    raw = job_to_dict(job)
    back = job_from_dict(raw)
    assert back.multiregion.strategy.max_parallel == 2
    assert back.multiregion.regions[0].name == "west"
    assert back.multiregion.regions[0].count == 4
