"""Scaling policy + scaling event tests (reference model:
nomad/job_endpoint.go Job.Scale / ScaleStatus,
nomad/state/state_store.go scaling_policy tables,
command/scaling_policy_list.go).
"""
import json
import time
import urllib.request

import pytest

from nomad_tpu import jobspec, mock
from nomad_tpu.api import start_http_server
from nomad_tpu.server import Server
from nomad_tpu.server.fsm import install_payload, state_payload
from nomad_tpu.state import StateStore
from nomad_tpu.structs import ScalingPolicy


def make_scaled_job(min_=1, max_=5, count=2):
    j = mock.job()
    j.task_groups[0].scaling = ScalingPolicy(
        min=min_, max=max_, policy={"cooldown": "1m"}
    )
    j.task_groups[0].count = count
    return j


@pytest.fixture
def srv():
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=7)
    server.start()
    yield server
    server.stop()


def test_policy_derived_on_register(srv):
    j = make_scaled_job()
    srv.register_job(j)
    pols = srv.store.iter_scaling_policies()
    assert len(pols) == 1
    p = pols[0]
    assert p.target == {
        "Namespace": j.namespace,
        "Job": j.id,
        "Group": j.task_groups[0].name,
    }
    assert p.min == 1 and p.max == 5
    assert srv.store.scaling_policy_by_id(p.id) is p
    assert (
        srv.store.scaling_policy_by_target(
            j.namespace, j.id, j.task_groups[0].name
        )
        is p
    )


def test_policy_id_stable_across_job_updates(srv):
    j = make_scaled_job()
    srv.register_job(j)
    pid = srv.store.iter_scaling_policies()[0].id
    j2 = make_scaled_job(max_=10)
    j2.id = j.id
    srv.register_job(j2)
    pols = srv.store.iter_scaling_policies()
    assert len(pols) == 1
    assert pols[0].id == pid
    assert pols[0].max == 10


def test_policy_dies_with_job(srv):
    j = make_scaled_job()
    srv.register_job(j)
    assert srv.store.iter_scaling_policies()
    srv.deregister_job(j.namespace, j.id, purge=True)
    assert not srv.store.iter_scaling_policies()


def test_scale_within_bounds_creates_eval_and_event(srv):
    j = make_scaled_job()
    srv.register_job(j)
    group = j.task_groups[0].name
    ev, event = srv.scale_job(
        j.namespace, j.id, group, count=4, message="scale up"
    )
    assert ev is not None
    assert event.count == 4 and event.previous_count == 2
    assert event.eval_id == ev.id
    job = srv.store.job_by_id(j.namespace, j.id)
    assert job.lookup_task_group(group).count == 4
    events = srv.store.scaling_events_for_job(j.namespace, j.id)
    assert [e.count for e in events[group]] == [4]


def test_scale_outside_bounds_rejected(srv):
    j = make_scaled_job(min_=2, max_=3)
    srv.register_job(j)
    group = j.task_groups[0].name
    with pytest.raises(ValueError):
        srv.scale_job(j.namespace, j.id, group, count=9)
    with pytest.raises(ValueError):
        srv.scale_job(j.namespace, j.id, group, count=1)
    # policy override bypasses bounds (reference PolicyOverride)
    ev, _ = srv.scale_job(
        j.namespace, j.id, group, count=9, policy_override=True
    )
    assert ev is not None


def test_scale_event_only_when_count_none(srv):
    j = make_scaled_job()
    srv.register_job(j)
    group = j.task_groups[0].name
    before = srv.store.job_by_id(j.namespace, j.id).modify_index
    ev, event = srv.scale_job(
        j.namespace, j.id, group, message="autoscaler: at target",
    )
    assert ev is None and event.count is None
    # the job itself is untouched
    assert srv.store.job_by_id(j.namespace, j.id).modify_index == before
    events = srv.store.scaling_events_for_job(j.namespace, j.id)
    assert events[group][0].message == "autoscaler: at target"


def test_event_retention_cap(srv):
    from nomad_tpu.structs import JOB_TRACKED_SCALING_EVENTS, ScalingEvent

    j = make_scaled_job()
    srv.register_job(j)
    group = j.task_groups[0].name
    for i in range(JOB_TRACKED_SCALING_EVENTS + 5):
        srv.store.upsert_scaling_event(
            j.namespace, j.id, group, ScalingEvent(message=f"e{i}")
        )
    events = srv.store.scaling_events_for_job(j.namespace, j.id)[group]
    assert len(events) == JOB_TRACKED_SCALING_EVENTS
    # newest first
    assert events[0].message == f"e{JOB_TRACKED_SCALING_EVENTS + 4}"


def test_scaling_survives_snapshot_roundtrip(srv):
    j = make_scaled_job()
    srv.register_job(j)
    group = j.task_groups[0].name
    srv.scale_job(j.namespace, j.id, group, count=3, message="up")
    payload = state_payload(srv.store, None)
    fresh = StateStore()
    install_payload(fresh, None, payload)
    pols = fresh.iter_scaling_policies()
    assert len(pols) == 1 and pols[0].max == 5
    assert fresh.scaling_policy_by_target(j.namespace, j.id, group)
    events = fresh.scaling_events_for_job(j.namespace, j.id)
    assert events[group][0].count == 3


HCL_SCALED = """
job "horizontal" {
  group "web" {
    count = 2
    scaling {
      enabled = true
      min = 1
      max = 8
      policy {
        cooldown = "2m"
      }
    }
    task "t" {
      driver = "mock_driver"
      resources { cpu = 100 memory = 64 }
    }
  }
}
"""


def test_jobspec_scaling_block():
    job = jobspec.parse(HCL_SCALED)
    sc = job.task_groups[0].scaling
    assert sc is not None
    assert sc.min == 1 and sc.max == 8 and sc.enabled
    assert sc.policy.get("cooldown") == "2m"


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())


def _post(base, path, body, method="POST"):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


@pytest.fixture
def api():
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=33)
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    yield server, base
    http.stop()
    server.stop()


def test_scaling_http_surface(api):
    server, base = api
    j = make_scaled_job()
    server.register_job(j)
    group = j.task_groups[0].name

    pols = _get(base, "/v1/scaling/policies")
    assert len(pols) == 1
    assert pols[0]["Target"]["Group"] == group
    assert "Policy" not in pols[0]  # list returns stubs

    pol = _get(base, f"/v1/scaling/policy/{pols[0]['ID']}")
    assert pol["Policy"] == {"cooldown": "1m"}
    assert pol["Min"] == 1 and pol["Max"] == 5

    resp = _post(
        base,
        f"/v1/job/{j.id}/scale",
        {"Target": {"Group": group}, "Count": 3, "Message": "via api"},
    )
    assert resp["EvalID"]

    status = _get(base, f"/v1/job/{j.id}/scale")
    assert status["JobID"] == j.id
    tg = status["TaskGroups"][group]
    assert tg["Desired"] == 3
    assert tg["Events"][0]["Count"] == 3
    assert tg["Events"][0]["Message"] == "via api"
