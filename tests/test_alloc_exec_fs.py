"""Alloc exec + fs proxying, node purge, built-in UI (reference
command/alloc_exec.go, client fs endpoints, node_endpoint.go
Node.Deregister, ui/).
"""
import json
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.api import start_http_server
from nomad_tpu.client import Client
from nomad_tpu.server import Server
from nomad_tpu.structs import Node, Task


def wait_until(cond, timeout=10.0, interval=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        ct = resp.headers.get("Content-Type", "")
        data = resp.read()
        return json.loads(data) if "json" in ct else data


def _post(base, path, body):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


@pytest.fixture
def stack(tmp_path):
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=11)
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    cli = Client(
        server, node=Node(), data_dir=str(tmp_path),
        heartbeat_interval=5.0,
    )
    cli.start()
    yield server, cli, base
    cli.stop()
    http.stop()
    server.stop()


def _run_job(server, job_id, config=None, driver="raw_exec"):
    job = mock.job(id=job_id)
    tg = job.task_groups[0]
    tg.count = 1
    tg.tasks[0] = Task(
        name="main",
        driver=driver,
        config=config
        or {"command": "/bin/sh", "args": ["-c", "sleep 30"]},
    )
    server.register_job(job)
    assert server.drain_to_idle(10)
    assert wait_until(
        lambda: any(
            a.client_status == "running"
            for a in server.store.allocs_by_job("default", job_id)
        )
    ), f"{job_id} never running"
    return server.store.allocs_by_job("default", job_id)[0]


def test_alloc_exec_runs_in_task_context(stack):
    server, _cli, base = stack
    alloc = _run_job(server, "execjob")
    resp = _post(
        base,
        f"/v1/client/allocation/{alloc.id}/exec",
        {"Task": "main", "Cmd": ["/bin/sh", "-c",
                                 "echo ctx=$NOMAD_ALLOC_ID; pwd"]},
    )
    assert resp["ExitCode"] == 0
    assert f"ctx={alloc.id}" in resp["Output"]
    # cwd is the task's local dir
    assert "/main/local" in resp["Output"]

    # unknown task -> 404
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(
            base,
            f"/v1/client/allocation/{alloc.id}/exec",
            {"Task": "nope", "Cmd": ["true"]},
        )
    assert exc.value.code == 404


def test_alloc_exec_nonzero_exit(stack):
    server, _cli, base = stack
    alloc = _run_job(server, "execrc")
    resp = _post(
        base,
        f"/v1/client/allocation/{alloc.id}/exec",
        {"Task": "main", "Cmd": ["/bin/sh", "-c", "exit 3"]},
    )
    assert resp["ExitCode"] == 3


def test_alloc_fs_ls_and_cat(stack):
    server, _cli, base = stack
    alloc = _run_job(
        server,
        "fsjob",
        config={
            "command": "/bin/sh",
            "args": [
                "-c",
                "echo file-content > \"$NOMAD_TASK_DIR/out.txt\"; "
                "sleep 30",
            ],
        },
    )
    assert wait_until(
        lambda: any(
            e["Name"] == "out.txt"
            for e in server.list_alloc_files(
                alloc.id, "main/local"
            )
        )
    )
    entries = _get(base, f"/v1/client/fs/ls/{alloc.id}?path=")
    names = [e["Name"] for e in entries]
    assert "alloc" in names and "main" in names
    data = _get(
        base,
        f"/v1/client/fs/cat/{alloc.id}?path=main/local/out.txt",
    )
    assert data["Data"].strip() == "file-content"
    # escapes rejected (400 from ValueError)
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(
            base,
            f"/v1/client/fs/cat/{alloc.id}?path=../../etc/passwd",
        )
    assert exc.value.code == 400


def test_node_purge(stack):
    server, cli, base = stack
    alloc = _run_job(server, "purgejob")
    node_id = cli.node.id
    resp = _post(base, f"/v1/node/{node_id}/purge", {})
    assert resp["EvalIDs"]
    assert server.store.node_by_id(node_id) is None
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base, f"/v1/node/{node_id}/purge", {})
    assert exc.value.code == 404


def test_ui_served(stack):
    _server, _cli, base = stack
    html = _get(base, "/ui").decode()
    assert "<title>nomad-tpu</title>" in html
    assert "/v1/jobs" in html


def test_cli_alloc_exec_and_fs(stack, monkeypatch, capsys):
    from nomad_tpu.cli import main

    server, _cli, base = stack
    monkeypatch.setenv("NOMAD_ADDR", base)
    alloc = _run_job(server, "cliexec")
    with pytest.raises(SystemExit) as exc:
        main(["alloc", "exec", "-task", "main", alloc.id,
              "/bin/sh", "-c", "echo from-exec"])
    assert exc.value.code == 0
    assert "from-exec" in capsys.readouterr().out

    main(["alloc", "fs", alloc.id])
    out = capsys.readouterr().out
    assert "alloc" in out and "main" in out
