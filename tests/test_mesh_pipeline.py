"""Sharded hot-path tests (NOMAD_TPU_MESH=1, 8-device virtual CPU
mesh): the sharded device-resident usage mirror must stay
bit-identical to host state across the full lifecycle (the PR 1
parity suite re-run sharded), a warm mesh flush must ship O(dirty
rows) bytes instead of O(nodes) columns (the `mesh.bytes_per_flush`
acceptance gauge), and a mid-chain device failover must flush the
sharded mirror, drop the chain cleanly, and finish every eval on the
CPU fallback with unsharded-identical decisions.
"""
import copy
import random

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server
from nomad_tpu.structs import compute_node_class


@pytest.fixture(autouse=True)
def _mesh_env(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_MESH", "1")


def make_nodes(n, seed=0):
    rng = random.Random(seed)
    nodes = []
    for i in range(n):
        node = mock.node(id=f"mesh-node-{seed}-{i}")
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.node_resources.memory_mb = rng.choice([8192, 16384])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    return nodes


def make_jobs(n, prefix="mesh", seed=1):
    rng = random.Random(seed)
    jobs = []
    for i in range(n):
        job = mock.job(id=f"{prefix}-{i}")
        job.task_groups[0].count = rng.randint(1, 4)
        job.task_groups[0].tasks[0].resources.cpu = rng.choice(
            [200, 400]
        )
        jobs.append(job)
    return jobs


def placements(server, job_id):
    return sorted(
        (a.name, a.node_id)
        for a in server.store.allocs_by_job("default", job_id)
        if not a.terminal_status()
    )


def host_columns(table):
    return (
        table.cpu_total, table.mem_total, table.disk_total,
        table.cpu_used, table.mem_used, table.disk_used,
    )


def test_sharded_mirror_delta_patch_bit_identical():
    """The SHARDED usage mirror, delta-patched per shard from the
    store's dirty-row log, must stay bit-identical to the live host
    columns after a plan commit, a node drain, a node register and a
    driver re-fingerprint — and its arrays must actually be sharded
    P("nodes") over the full virtual mesh."""
    import jax

    bat = Server(num_schedulers=1, seed=31, batch_pipeline=True)
    bat.start()
    try:
        nodes = make_nodes(10, seed=5)
        for node in nodes:
            bat.register_node(node)
        worker = bat.workers[0]
        assert worker._mesh is not None, (
            "no mesh on the 8-device virtual host"
        )
        n_dev = worker._mesh.devices.size
        assert n_dev == len(jax.devices()) == 8
        table = bat.store.node_table

        def assert_mirror_exact(label):
            cols = worker._device_columns(table, sharded=True)
            for got, want in zip(cols, host_columns(table)):
                np.testing.assert_array_equal(
                    np.asarray(got), want, err_msg=label
                )
                # really sharded: one node-axis shard per device
                assert len(got.sharding.device_set) == n_dev, label
                shard_rows = {
                    s.data.shape[0] for s in got.addressable_shards
                }
                assert shard_rows == {table.capacity // n_dev}, label

        assert_mirror_exact("initial sync")

        # plan commit: usage changes, topology doesn't -> the
        # per-shard dirty-row patch must reproduce the columns exactly
        for job in make_jobs(3, seed=9):
            bat.register_job(job)
        assert bat.drain_to_idle(30)
        assert_mirror_exact("after plan commit")
        assert worker._mesh_mirror_hits > 0, (
            worker._mesh_mirror_hits, worker._mesh_mirror_misses
        )

        # node drain: topology generation bumps -> full resync
        bat.store.update_node_drain(nodes[0].id, True)
        assert_mirror_exact("after node drain")

        # node register: arena may grow / new row
        extra = make_nodes(1, seed=77)[0]
        bat.register_node(extra)
        assert_mirror_exact("after node register")

        # driver re-fingerprint: re-upsert with changed attributes
        refp = nodes[1]
        refp.attributes = dict(refp.attributes)
        refp.attributes["driver.raw_exec"] = "1"
        bat.store.upsert_node(refp)
        assert_mirror_exact("after driver re-fingerprint")

        # steady state again: another commit after the topo churn
        for job in make_jobs(2, seed=13):
            job.id = job.id + "-post"
            bat.register_job(job)
        assert bat.drain_to_idle(30)
        assert_mirror_exact("after post-churn commit")

        # both mirrors coexist and are independently consistent
        plain = worker._device_columns(table)
        for got, want in zip(plain, host_columns(table)):
            np.testing.assert_array_equal(np.asarray(got), want)
    finally:
        bat.stop()


def test_sharded_mirror_warm_flush_ships_o_dirty_rows_bytes():
    """The acceptance gauge: a warm sharded sync after a small usage
    delta stages O(dirty rows) bytes (pow2-padded idx + three value
    buffers), NOT the six O(nodes) columns a cold sync uploads."""
    from nomad_tpu.ops.batch import pow2_bucket

    bat = Server(num_schedulers=1, seed=7, batch_pipeline=True)
    bat.start()
    try:
        for node in make_nodes(12, seed=3):
            bat.register_node(node)
        worker = bat.workers[0]
        assert worker._mesh is not None
        table = bat.store.node_table
        full_bytes = sum(c.nbytes for c in host_columns(table))

        # cold sync: the full upload, and the gauge says so
        worker._device_columns(table, sharded=True)
        assert (
            bat.metrics.get_gauge("mesh.bytes_per_flush")
            == full_bytes
        )

        # dirty a couple of rows through the real alloc-lifecycle
        # write path, then re-sync warm.  The worker's own flushes
        # may have delta-synced already — measure against whatever
        # the cache has left to catch up on, so the expected staging
        # width is deterministic either way
        for job in make_jobs(1, seed=41):
            bat.register_job(job)
        assert bat.drain_to_idle(30)
        _, dirty = bat.store.usage_delta_since(
            worker._usage_cache_sharded["gen"]
        )
        worker._device_columns(table, sharded=True)
        staged = bat.metrics.get_gauge("mesh.bytes_per_flush")
        if not dirty:
            # the worker's own flush synced past the commit already
            # and nothing is dirty now: the warm re-sync ships zero
            assert staged == 0.0
        else:
            width = pow2_bucket(len(dirty), floor=8)
            # three used columns x (i32 idx + f64 vals), all padded
            # to the pow2 staging bucket
            assert staged == 3 * (width * 4 + width * 8)
        assert staged < full_bytes / 2
        assert bat.metrics.get_gauge("mesh.mirror_hit_rate") > 0.0
    finally:
        bat.stop()


def test_mesh_mid_chain_failover_flushes_sharded_mirror(monkeypatch):
    """A supervisor backend flip mid-chain on a mesh worker: the REAL
    transition listener must flush the sharded mirror and disable the
    mesh, the in-flight sharded chain must drop cleanly, and every
    eval — gulped AND admitted — must complete on the CPU fallback
    with decisions identical to an unsharded fresh-gulp run (zero
    lost)."""
    jobs = make_jobs(8, prefix="mflip", seed=17)
    nodes = make_nodes(16, seed=3)

    adm = Server(num_schedulers=1, seed=33, batch_pipeline=True)
    worker = adm.workers[0]
    assert worker._mesh is not None
    late = [copy.deepcopy(j) for j in jobs[4:]]
    fired = []
    orig_launch = worker._launch_chunk

    def hooked(asm, c0, c1, carry, check_ready):
        fired.append(asm.use_mesh)
        if len(fired) == 1:
            for job in late:
                adm.register_job(job)
        out = orig_launch(asm, c0, c1, carry, check_ready)
        if len(fired) == 2:
            # simulate the supervisor's failover through the REAL
            # listener (not a bare epoch bump): sharded mirror
            # flushed, mesh off, chain epoch invalidated
            sup = worker.supervisor
            sup.backend_epoch += 1
            sup._state = "LOST"
            worker._on_device_transition("device", "cpu", "test")
        return out

    worker._launch_chunk = hooked
    for node in nodes:
        adm.register_node(copy.deepcopy(node))
    for job in jobs[:4]:
        adm.register_job(copy.deepcopy(job))
    adm.start()
    try:
        assert adm.drain_to_idle(60)
        assert any(fired), "the sharded launch never ran"
        # the listener flushed the sharded mirror and took the mesh
        # down; later syncs go through the plain CPU mirror
        assert worker._mesh is None
        assert worker._usage_cache_sharded is None
        assert worker._mirror_dirty_sharded
        assert worker._backend_epoch == 1
        # zero lost: every eval completed exactly once
        evs = [
            e
            for e in adm.store.evals.values()
            if e.job_id.startswith("mflip-")
        ]
        assert len(evs) >= len(jobs)
        assert all(e.status == "complete" for e in evs)
        adm_p = {j.id: placements(adm, j.id) for j in jobs}
    finally:
        adm.stop()

    monkeypatch.setenv("NOMAD_TPU_MESH", "0")
    fresh = Server(num_schedulers=1, seed=33, batch_pipeline=True)
    for node in nodes:
        fresh.register_node(copy.deepcopy(node))
    fresh.start()
    try:
        for job in jobs[:4]:
            fresh.register_job(copy.deepcopy(job))
        assert fresh.drain_to_idle(60)
        for job in jobs[4:]:
            fresh.register_job(copy.deepcopy(job))
        assert fresh.drain_to_idle(60)
        for job in jobs:
            assert adm_p[job.id] == placements(
                fresh, job.id
            ), f"divergence for {job.id}"
    finally:
        fresh.stop()
