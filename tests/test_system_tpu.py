"""TPUSystemStack parity: the vectorized system stack must produce
plans identical to the oracle SystemStack, and beat it at fleet scale
(VERDICT r1 item 4; reference scheduler/system_sched.go:54,
stack.go:182-318 — system jobs score every feasible node, no visit
limit, which makes the per-node checker chain the dominant cost)."""
import random
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.sched.system_sched import SystemScheduler
from nomad_tpu.sched.testing import Harness
from nomad_tpu.structs import Constraint, compute_node_class


def build_fleet(h, n, seed=3):
    rng = random.Random(seed)
    nodes = []
    for i in range(n):
        node = mock.node()
        node.datacenter = rng.choice(["dc1", "dc2"])
        node.node_class = rng.choice(["web", "db", "cache"])
        node.attributes["kernel.version"] = rng.choice(
            ["4.19", "5.4", "5.10"]
        )
        node.meta["rack"] = f"r{rng.randrange(8)}"
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.node_resources.memory_mb = rng.choice([8192, 16384])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
        h.store.upsert_node(node)
    return nodes


def system_job(jid, count_constraints=True):
    job = mock.system_job(id=jid)
    job.datacenters = ["dc1", "dc2"]
    tg = job.task_groups[0]
    tg.tasks[0].resources.cpu = 100
    tg.tasks[0].resources.memory_mb = 128
    if count_constraints:
        job.constraints = [
            Constraint(
                ltarget="${node.class}", operand="=", rtarget="web"
            ),
            Constraint(
                ltarget="${attr.kernel.version}",
                operand="version",
                rtarget=">= 5.0",
            ),
        ]
    return job


def run(h, job, use_tpu, seed=11):
    h.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id, type="system")
    h.process(SystemScheduler, ev, use_tpu=use_tpu, seed=seed)
    plan = h.plans[-1]
    placed = sorted(
        (a.name, a.node_id)
        for v in plan.node_allocation.values()
        for a in v
    )
    return plan, placed


def plan_view(h):
    return [
        (
            sorted(
                (a.name, a.node_id)
                for v in p.node_allocation.values()
                for a in v
            ),
            sorted(
                (a.name, a.node_id, a.desired_status)
                for v in p.node_update.values()
                for a in v
            ),
        )
        for p in h.plans
    ]


def test_system_parity_constrained_fleet():
    ha = Harness()
    hb = Harness()
    seed_nodes = build_fleet(ha, 120, seed=5)
    for n in seed_nodes:
        hb.store.upsert_node(n)

    _plan_a, placed_a = run(ha, system_job("sys-a"), use_tpu=False)
    _plan_b, placed_b = run(hb, system_job("sys-a"), use_tpu=True)
    assert placed_a == placed_b
    assert len(placed_a) > 0
    # only class=web kernel>=5.0 nodes got an alloc
    by_id = {n.id: n for n in seed_nodes}
    for _name, nid in placed_b:
        assert by_id[nid].node_class == "web"
        assert by_id[nid].attributes["kernel.version"] != "4.19"
    # evals/blocked bookkeeping identical
    assert len(ha.evals) == len(hb.evals)


def test_system_parity_unconstrained_and_exhausted():
    """Unconstrained system job: places everywhere with capacity;
    exhausted nodes produce identical blocked-eval behavior."""
    ha = Harness()
    hb = Harness()
    rng = random.Random(9)
    for i in range(40):
        node = mock.node()
        node.node_resources.cpu = 150 if i % 5 == 0 else 4000
        node.node_resources.memory_mb = 8192
        node.computed_class = compute_node_class(node)
        ha.store.upsert_node(node)
        hb.store.upsert_node(node)

    job = system_job("sys-x", count_constraints=False)
    job.task_groups[0].tasks[0].resources.cpu = 200  # too big for 150
    _pa, placed_a = run(ha, job, use_tpu=False)
    _pb, placed_b = run(hb, system_job("sys-x", False), use_tpu=True)
    # tweak: second harness must see identical job definition
    assert placed_a == placed_b
    assert len(ha.evals) == len(hb.evals)
    assert plan_view(ha) == plan_view(hb)


def test_system_parity_update_and_node_down():
    """Steady state: job update (destructive) + node down produce
    identical stops and replacements."""
    ha = Harness()
    hb = Harness()
    nodes = build_fleet(ha, 60, seed=13)
    for n in nodes:
        hb.store.upsert_node(n)

    for h in (ha, hb):
        _plan, placed = run(h, system_job("sys-u"), use_tpu=h is hb)
        # apply placements so the update pass sees live allocs
        assert len(placed) > 0

    # job update: changed env forces destructive update
    for h, tpu in ((ha, False), (hb, True)):
        job2 = system_job("sys-u")
        job2.version = 1
        job2.task_groups[0].tasks[0].env = {"V": "2"}
        run(h, job2, use_tpu=tpu)

    assert plan_view(ha) == plan_view(hb)


def heavy_system_job(jid):
    """Constraint-heavy system job: the shape where the per-node
    checker walk dominates (regex/version/meta checks per node)."""
    job = system_job(jid)
    job.constraints += [
        Constraint(
            ltarget="${meta.rack}", operand="regexp", rtarget="^r[0-6]$"
        ),
        Constraint(
            ltarget="${node.datacenter}",
            operand="set_contains_any",
            rtarget="dc1,dc2",
        ),
        Constraint(ltarget="${attr.kernel.version}", operand="is_set"),
    ]
    return job


@pytest.mark.slow
def test_system_vectorized_faster_at_scale():
    """The point of the vectorized stack: at fleet scale one mask pass
    beats walking every node through the checker chain."""
    ha = Harness()
    hb = Harness()
    nodes = build_fleet(ha, 3000, seed=21)
    for n in nodes:
        hb.store.upsert_node(n)

    # warm both: columns interned, regex caches populated
    run(ha, heavy_system_job("sys-warm"), use_tpu=False)
    run(hb, heavy_system_job("sys-warm"), use_tpu=True)

    # best-of-3 to shrug off CI scheduling noise
    t_oracle = t_tpu = float("inf")
    placed_a = placed_b = None
    for i in range(3):
        start = time.perf_counter()
        _pa, placed_a = run(
            ha, heavy_system_job(f"sys-big-{i}"), use_tpu=False
        )
        t_oracle = min(t_oracle, time.perf_counter() - start)
        start = time.perf_counter()
        _pb, placed_b = run(
            hb, heavy_system_job(f"sys-big-{i}"), use_tpu=True
        )
        t_tpu = min(t_tpu, time.perf_counter() - start)

    assert placed_a == placed_b
    assert len(placed_a) > 100
    # generous margin to keep CI stable
    assert t_tpu < t_oracle, (
        f"vectorized system stack slower: {t_tpu:.3f}s vs oracle "
        f"{t_oracle:.3f}s"
    )
