"""Concurrency stress tests with invariant checks — the framework's
race-detection tooling (SURVEY §5: the reference leans on go test
-race; Python has no tsan, so these tests drive the hot shared
structures from many threads and assert the invariants that a race
would break).
"""
import random
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import EvalBroker, Server
from nomad_tpu.server.plan_apply import PlanApplier
from nomad_tpu.server.plan_queue import PlanQueue
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    AllocatedResources,
    AllocatedTaskResources,
    Plan,
    allocs_fit,
)


def _resources(cpu, mem):
    return AllocatedResources(
        tasks={"t": AllocatedTaskResources(cpu=cpu, memory_mb=mem)}
    )


def test_broker_concurrent_producers_consumers():
    """Storm the broker from both sides: every eval must be delivered
    and acked exactly once; nacks redeliver; nothing deadlocks."""
    # a high delivery limit: with random nacks, the default limit of 3
    # would (correctly!) route unlucky evals to the failed queue —
    # this test asserts exactly-once delivery, not the failure policy
    broker = EvalBroker(nack_timeout=5.0, delivery_limit=1_000_000)
    broker.set_enabled(True)
    N_PRODUCERS, EVALS_EACH, N_CONSUMERS = 4, 50, 4
    total = N_PRODUCERS * EVALS_EACH
    acked = []
    acked_lock = threading.Lock()
    stop = threading.Event()

    def producer(p):
        for i in range(EVALS_EACH):
            # distinct job ids so JobID dedup doesn't serialize the test
            broker.enqueue(
                mock.evaluation(job_id=f"job-{p}-{i}", priority=(i % 3) * 40)
            )

    def consumer(c):
        rng = random.Random(c)
        while not stop.is_set():
            ev, token = broker.dequeue(["service"], timeout=0.2)
            if ev is None:
                continue
            if rng.random() < 0.05:
                broker.nack(ev.id, token)  # redelivered later
                continue
            with acked_lock:
                acked.append(ev.id)
            broker.ack(ev.id, token)

    producers = [
        threading.Thread(target=producer, args=(p,))
        for p in range(N_PRODUCERS)
    ]
    consumers = [
        threading.Thread(target=consumer, args=(c,), daemon=True)
        for c in range(N_CONSUMERS)
    ]
    for t in producers + consumers:
        t.start()
    for t in producers:
        t.join()
    # generous under CPU contention: the invariant is exactly-once,
    # not speed
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline and len(acked) < total:
        time.sleep(0.05)
    stop.set()
    for t in consumers:
        t.join(timeout=2)
    assert len(acked) == total
    assert len(set(acked)) == total, "an eval was delivered-acked twice"


def test_pipelined_applier_never_overcommits_under_storm():
    """Many submitters race conflicting plans through the pipelined
    applier (optimistic overlay + epoch invalidation): after the dust
    settles, every node's live allocations must still fit — the
    invariant the serialized applier exists to protect."""
    store = StateStore()
    nodes = [mock.node() for _ in range(6)]
    for n in nodes:
        store.upsert_node(n)
    pq = PlanQueue()
    pq.set_enabled(True)
    applier = PlanApplier(store, pq)
    applier.start()
    N_THREADS, PLANS_EACH = 6, 15
    results = []
    res_lock = threading.Lock()

    def submitter(s):
        rng = random.Random(s)
        for i in range(PLANS_EACH):
            node = rng.choice(nodes)
            alloc = mock.alloc(node_id=node.id)
            # big enough that only ~2 fit per node: plenty of conflicts
            alloc.allocated_resources = _resources(1500, 3000)
            plan = Plan(
                node_allocation={node.id: [alloc]},
                priority=rng.choice([30, 50, 70]),
            )
            try:
                pending = pq.enqueue(plan)
                result = pending.wait(timeout=30)
                with res_lock:
                    results.append(result)
            except (RuntimeError, TimeoutError) as exc:
                with res_lock:
                    results.append(exc)

    threads = [
        threading.Thread(target=submitter, args=(s,))
        for s in range(N_THREADS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    applier.stop()
    assert all(not t.is_alive() for t in threads), "submitter hung"
    assert len(results) == N_THREADS * PLANS_EACH
    assert not any(isinstance(r, Exception) for r in results), [
        r for r in results if isinstance(r, Exception)
    ][:3]
    # THE invariant: no node is overcommitted
    for n in nodes:
        live = [
            a for a in store.allocs_by_node(n.id)
            if not a.terminal_status()
        ]
        fit, dim, _ = allocs_fit(n, live)
        assert fit, f"node {n.id[:8]} overcommitted ({dim})"
    committed = sum(
        1 for r in results if r.node_allocation
    )
    rejected = sum(
        1 for r in results if not r.node_allocation
    )
    # both outcomes must occur, or the conflict scenario didn't happen
    assert committed >= 6
    assert rejected >= 1, "storm produced no conflicts; weaken resources"


def test_store_blocking_queries_with_concurrent_writes():
    """Readers long-poll while writers churn: indexes observed by any
    reader are monotonic and every write eventually wakes waiters."""
    store = StateStore()
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            store.upsert_node(mock.node())
            i += 1
            time.sleep(0.002)

    def reader(r):
        last = 0
        while not stop.is_set():
            woke = store.wait_for_index(last + 1, timeout=0.5)
            idx = store.latest_index()
            if idx < last:
                errors.append(f"index went backwards {last}->{idx}")
                return
            if woke and idx <= last:
                errors.append(
                    f"woken without progress at {last} (idx {idx})"
                )
                return
            last = idx

    threads = [threading.Thread(target=writer) for _ in range(2)] + [
        threading.Thread(target=reader, args=(r,)) for r in range(4)
    ]
    for t in threads:
        t.start()
    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors, errors


@pytest.mark.slow
def test_server_concurrent_job_registration_storm():
    """Register jobs from many threads against a live server; every
    job either fully places or produces a blocked eval — nothing is
    lost and the final allocation set fits every node."""
    server = Server(num_schedulers=2, heartbeat_ttl=60.0, seed=5)
    server.start()
    try:
        for _ in range(8):
            server.register_node(mock.node())
        N_THREADS, JOBS_EACH = 4, 6
        errors = []

        def register(tid):
            for i in range(JOBS_EACH):
                job = mock.job(id=f"storm-{tid}-{i}")
                job.task_groups[0].count = 2
                try:
                    server.register_job(job)
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

        threads = [
            threading.Thread(target=register, args=(t,))
            for t in range(N_THREADS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert server.drain_to_idle(30)
        total_placed = 0
        for tid in range(N_THREADS):
            for i in range(JOBS_EACH):
                allocs = [
                    a
                    for a in server.store.allocs_by_job(
                        "default", f"storm-{tid}-{i}"
                    )
                    if not a.terminal_status()
                ]
                evs = server.store.evals_by_job(
                    "default", f"storm-{tid}-{i}"
                )
                assert allocs or any(
                    e.status == "blocked" for e in evs
                ), f"job storm-{tid}-{i} vanished"
                total_placed += len(allocs)
        for n in server.store.iter_nodes():
            live = [
                a
                for a in server.store.allocs_by_node(n.id)
                if not a.terminal_status()
            ]
            fit, dim, _ = allocs_fit(n, live)
            assert fit, f"node overcommitted ({dim})"
        assert total_placed > 0
    finally:
        server.stop()
