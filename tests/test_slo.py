"""Control-loop flight data: the SLO engine's multi-window burn-rate
math (crossing WARN/BURNING in both directions), the bounded
adaptive-decision ledger (ring eviction, newest-first reads, per-site
record shapes from real decision sites, trace-id joins), the HTTP +
CLI surfaces (/v1/slo, /v1/decisions with filters, the cluster fan-in
variants), the operator debug bundle capture, and the
``NOMAD_TPU_SLO=0`` / ``NOMAD_TPU_DECISIONS=0`` opt-outs."""
import json
import time
import urllib.error
import urllib.request

from types import SimpleNamespace

import pytest

from nomad_tpu import mock
from nomad_tpu.api import start_http_server
from nomad_tpu.decisions import (
    DECISION_SITES,
    DECISIONS,
    DecisionLedger,
)
from nomad_tpu.server import Server
from nomad_tpu.server.cluster import TestCluster
from nomad_tpu.slo import SLOEngine
from nomad_tpu.structs import Evaluation
from nomad_tpu.telemetry import Metrics, MetricsHistory
from nomad_tpu.trace import TRACE


def wait_until(cond, timeout=30.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    raise AssertionError(f"timeout waiting for {msg}")


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())


@pytest.fixture(autouse=True)
def _fresh_ledger():
    """The ledger is a process-wide singleton (like TRACE): start
    every test from an empty, enabled ring so cross-test records
    can't satisfy an assertion here."""
    DECISIONS.set_enabled(True)
    DECISIONS.clear()
    yield
    DECISIONS.set_enabled(True)
    DECISIONS.clear()


# -- burn-rate math ----------------------------------------------------


def _win(counters=None, samples=None):
    return {
        "t": 0.0,
        "counters": dict(counters or {}),
        "gauges": {},
        "samples": dict(samples or {}),
    }


def _lat_win(p99):
    return _win(
        samples={
            "batch_worker.eval_latency_ms": {
                "count": 10, "p50": p99 / 2, "p99": p99,
            }
        }
    )


def _engine(windows, **env):
    hist = SimpleNamespace(
        to_dict=lambda: {
            "enabled": True,
            "interval_s": 15.0,
            "max_windows": 60,
            "windows": windows,
        }
    )
    return SLOEngine(Metrics(), hist)


def _obj(status, name):
    return next(
        o for o in status["objectives"] if o["name"] == name
    )


def test_latency_objective_burns_then_recovers(monkeypatch):
    """p99 over target in every window -> burn 1/budget = 20x in
    BOTH windows -> BURNING; once the fast window clears, the grade
    steps down (slow alone is history, not an alert)."""
    monkeypatch.setenv("NOMAD_TPU_SLO_FAST_N", "2")
    monkeypatch.setenv("NOMAD_TPU_SLO_SLOW_N", "6")
    hot = [_lat_win(900.0) for _ in range(6)]
    st = _engine(hot).status()
    obj = _obj(st, "interactive_placement_p99")
    assert obj["status"] == "BURNING"
    assert obj["burn_fast"] == pytest.approx(20.0)
    assert obj["burn_slow"] == pytest.approx(20.0)
    assert st["worst"] == "BURNING"

    # recovery direction: the last fast_n windows are clean, the slow
    # window still remembers the excursion -> WARN, not BURNING
    cooled = hot[:4] + [_lat_win(10.0), _lat_win(10.0)]
    st = _engine(cooled).status()
    obj = _obj(st, "interactive_placement_p99")
    assert obj["status"] == "WARN"
    assert obj["burn_fast"] == 0.0
    assert obj["burn_slow"] > 0.0

    # fully healed history grades OK
    st = _engine([_lat_win(10.0) for _ in range(6)]).status()
    assert _obj(st, "interactive_placement_p99")["status"] == "OK"
    assert st["worst"] == "OK"


def test_burning_requires_both_windows(monkeypatch):
    """A fast-only spike (noise) stays WARN even at 20x burn; only a
    spike that is also material over the slow window pages."""
    monkeypatch.setenv("NOMAD_TPU_SLO_FAST_N", "2")
    monkeypatch.setenv("NOMAD_TPU_SLO_SLOW_N", "30")
    spike = [_lat_win(10.0) for _ in range(28)] + [
        _lat_win(900.0), _lat_win(900.0),
    ]
    obj = _obj(
        _engine(spike).status(), "interactive_placement_p99"
    )
    assert obj["burn_fast"] == pytest.approx(20.0)
    assert obj["burn_fast"] >= 2.0 > obj["burn_slow"]
    assert obj["status"] == "WARN"


def test_zero_tolerance_and_ratio_objectives():
    """zero_lost_evals burns at the cap on ANY counter movement;
    shed_rate burns at shed/(shed+accepted)/budget."""
    quiet = [
        _win(counters={
            "broker.delivery_failures": 0,
            "overload.shed": 0,
            "overload.accepted": 100 * i,
        })
        for i in range(4)
    ]
    st = _engine(quiet).status()
    assert _obj(st, "zero_lost_evals")["status"] == "OK"
    assert _obj(st, "shed_rate")["status"] == "OK"

    bad = [
        _win(counters={
            "broker.delivery_failures": i,
            "overload.shed": 30 * i,
            "overload.accepted": 70 * i,
        })
        for i in range(4)
    ]
    st = _engine(bad).status()
    lost = _obj(st, "zero_lost_evals")
    assert lost["status"] == "BURNING"
    assert lost["burn_fast"] == 1000.0
    shed = _obj(st, "shed_rate")
    # 30% shed against a 5% budget = 6x burn in both windows
    assert shed["burn_fast"] == pytest.approx(6.0)
    assert shed["status"] == "BURNING"
    assert st["worst"] == "BURNING"


def test_empty_ring_never_pages():
    """<2 windows means no deltas and no rates: every objective OK —
    the engine must not page a freshly started server."""
    for windows in ([], [_lat_win(900.0)]):
        st = _engine(windows).status()
        assert st["worst"] == "OK"
        assert all(
            o["burn_fast"] == 0.0 for o in st["objectives"]
        )


def test_status_exports_slo_metrics():
    m = Metrics()
    hist = SimpleNamespace(
        to_dict=lambda: {"interval_s": 15.0, "windows": []}
    )
    engine = SLOEngine(m, hist)
    engine.status()
    engine.status()
    assert m.get_counter("slo.evaluations") == 2
    assert m.get_gauge("slo.worst") == 0.0


def test_slo_disabled_reports_inert(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_SLO", "0")
    st = _engine([_lat_win(900.0) for _ in range(6)]).status()
    assert st["enabled"] is False
    assert st["worst"] == "OK"
    assert all(o["burn_fast"] == 0.0 for o in st["objectives"])


# -- decision ledger ---------------------------------------------------


def test_ledger_ring_bounds_and_newest_first():
    led = DecisionLedger(ring=16)
    for i in range(40):
        led.record("chunk_width", f"width={i}")
    d = led.to_dict(limit=100)
    assert d["ring"]["depth"] == 16
    assert d["ring"]["cap"] == 16
    assert d["ring"]["evicted"] == 24
    # newest-first, oldest evicted
    actions = [r["action"] for r in d["decisions"]]
    assert actions[0] == "width=39"
    assert "width=0" not in actions
    # seq keeps counting across evictions
    assert d["decisions"][0]["seq"] == 40
    assert d["counts"] == {"chunk_width": 16}


def test_ledger_filters_and_trace_join():
    led = DecisionLedger(ring=64)
    led.record(
        "admission_defer", "defer",
        outcome="queue_closed", trace_id="ev-1",
    )
    led.record("overload_mode", "NORMAL->SHEDDING",
               outcome="escalate", trace_id="overload:7")
    led.record("admission_defer", "defer",
               outcome="assembly", trace_id="ev-2")
    assert [
        r["trace_id"] for r in led.recent(site="admission_defer")
    ] == ["ev-2", "ev-1"]
    assert [
        r["site"] for r in led.recent(outcome="escalate")
    ] == ["overload_mode"]
    # the trace filter is the join key back to /v1/traces/<id>
    assert [
        r["action"] for r in led.recent(trace="overload:7")
    ] == ["NORMAL->SHEDDING"]
    assert led.recent(trace="nope") == []


def test_ledger_record_shape_and_site_counters():
    led = DecisionLedger(ring=16)
    m = Metrics()
    rec = led.record(
        "storm_trigger", "drain_family",
        inputs={"family": "f", "drained": 3},
        alternatives=["serial_gulp"],
        trace_id="ev-9", metrics=m,
    )
    assert set(rec) == {
        "seq", "t", "site", "action", "inputs", "alternatives",
        "outcome", "trace_id",
    }
    assert rec["outcome"] == "applied"
    assert m.get_counter("decision.recorded") == 1
    assert m.get_counter("decision.site.storm_trigger") == 1
    assert m.get_gauge("decision.ring_depth") == 1.0


def test_ledger_disabled_is_inert(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_DECISIONS", "0")
    led = DecisionLedger(ring=16)
    m = Metrics()
    assert led.record("chunk_width", "width=4", metrics=m) is None
    d = led.to_dict()
    assert d["enabled"] is False
    assert d["ring"]["depth"] == 0
    assert m.get_counter("decision.recorded") == 0


def test_every_registered_site_has_counter():
    """Runtime mirror of the decision-ledger lint: the zero-registered
    family covers every site, so a fired site is always countable."""
    from nomad_tpu.decisions import DECISION_COUNTERS

    for slug in DECISION_SITES:
        assert f"decision.site.{slug}" in DECISION_COUNTERS


# -- real decision sites write real records ---------------------------


def _flood_broker(server, n):
    evals = [Evaluation(job_id=f"flood-{i}") for i in range(n)]
    server.store.upsert_evals(evals)
    server.broker.enqueue_all(evals)


def test_overload_transitions_ledger_and_trace_events(monkeypatch):
    """The mode ladder records an overload_mode decision per rung
    (inputs snapshot + alternatives + incident trace join) and
    broadcasts overload.mode_change onto in-flight traces."""
    monkeypatch.setenv("NOMAD_TPU_OVERLOAD_DEPTH", "4")
    TRACE.clear()
    server = Server(
        num_schedulers=1, heartbeat_ttl=60.0, seed=7,
        batch_pipeline=False,
    )
    server.start()
    try:
        for w in server.workers:
            w.stop()
        TRACE.begin("ev-inflight", queue="service")
        _flood_broker(server, 6)
        server.overload.evaluate(force=True)
        recs = DECISIONS.recent(site="overload_mode")
        assert recs, "escalation did not ledger"
        rec = recs[0]
        assert rec["action"] == "NORMAL->SHEDDING"
        assert rec["outcome"] == "escalate"
        assert rec["inputs"]["broker_depth"] >= 4
        assert "EMERGENCY" in rec["alternatives"]
        assert rec["trace_id"].startswith("overload:")
        # the incident trace id joins back to the ledger
        assert DECISIONS.recent(trace=rec["trace_id"])
        # satellite: in-flight traces got the mode_change event
        trace = TRACE.get("ev-inflight")
        events = [
            s for s in trace["spans"]
            if s["name"] == "overload.mode_change"
        ]
        assert events, trace["spans"]
        assert events[0]["attrs"]["new"] == "SHEDDING"

        server.broker.flush()
        wait_until(
            lambda: (
                server.overload.evaluate(force=True) == 0
            ),
            timeout=10.0,
            msg="recover to NORMAL",
        )
        outcomes = {
            r["outcome"]
            for r in DECISIONS.recent(site="overload_mode")
        }
        assert "recover" in outcomes
        assert server.metrics.get_counter(
            "decision.site.overload_mode"
        ) >= 2
    finally:
        server.stop()
        TRACE.clear()


def test_scheduling_load_ledgers_chunk_width():
    """A real placement round exercises the batch worker's
    chunk-width planner; change-only recording still yields the
    first-width record with the planner's inputs snapshot."""
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=7)
    server.start()
    try:
        for i in range(8):
            server.register_node(mock.node(id=f"slo-node-{i:02d}"))
        for i in range(4):
            job = mock.job(id=f"slo-job-{i}")
            job.task_groups[0].count = 1
            server.register_job(job)
        assert server.drain_to_idle(30)
        wait_until(
            lambda: DECISIONS.recent(site="chunk_width"),
            timeout=10.0,
            msg="chunk_width record",
        )
        rec = DECISIONS.recent(site="chunk_width")[0]
        assert rec["action"].startswith("width=")
        for key in (
            "n_evals", "backlog", "budget_ms", "leader_gen",
            "backend_epoch",
        ):
            assert key in rec["inputs"], rec["inputs"]
        assert rec["alternatives"], rec
    finally:
        server.stop()


# -- HTTP + cluster surfaces ------------------------------------------


@pytest.fixture
def api():
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=7)
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    yield server, base
    http.stop()
    server.stop()


def test_http_slo_endpoint(api):
    server, base = api
    server.metrics_history.snapshot_once()
    server.metrics_history.snapshot_once()
    st = _get(base, "/v1/slo")
    assert st["enabled"] is True
    assert len(st["objectives"]) >= 5
    assert {o["name"] for o in st["objectives"]} >= {
        "interactive_placement_p99",
        "zero_lost_evals",
        "shed_rate",
        "storm_fallback_rate",
        "failover_detect_to_resume",
    }
    assert st["worst"] in ("OK", "WARN", "BURNING")
    assert st["windows"]["retained"] >= 2


def test_http_decisions_endpoint_filters(api):
    server, base = api
    DECISIONS.record(
        "fanout_nack", "refresh_wait",
        outcome="partial_commit", trace_id="ev-x",
        metrics=server.metrics,
    )
    DECISIONS.record(
        "watchdog_budget", "trip", outcome="lost",
        metrics=server.metrics,
    )
    d = _get(base, "/v1/decisions")
    assert d["enabled"] is True
    assert len(d["decisions"]) == 2
    assert d["sites"] == sorted(DECISION_SITES)
    only = _get(base, "/v1/decisions?site=fanout_nack")
    assert [
        r["site"] for r in only["decisions"]
    ] == ["fanout_nack"]
    by_trace = _get(base, "/v1/decisions?trace=ev-x")
    assert len(by_trace["decisions"]) == 1
    by_outcome = _get(base, "/v1/decisions?outcome=lost")
    assert [
        r["site"] for r in by_outcome["decisions"]
    ] == ["watchdog_budget"]
    try:
        urllib.request.urlopen(
            base + "/v1/decisions?limit=bogus", timeout=10
        )
        assert False, "expected 400"
    except urllib.error.HTTPError as exc:
        assert exc.code == 400


def test_cluster_slo_and_decisions_fanin(monkeypatch):
    """Any server answers /v1/cluster/slo per-server and
    /v1/cluster/decisions as one seq-deduplicated merged ledger
    (the ledger is process-wide in TestCluster, so without the dedup
    every record would appear 3x)."""
    monkeypatch.setenv("NOMAD_TPU_OBS_FANIN_TIMEOUT_S", "2.0")
    cluster = TestCluster(3, heartbeat_ttl=300.0)
    cluster.start()
    http = None
    try:
        leader = cluster.wait_for_leader(timeout=30.0)
        http = start_http_server(leader, port=0)
        base = f"http://127.0.0.1:{http.port}"
        DECISIONS.record(
            "federation_retry", "pick=west",
            metrics=leader.metrics,
        )
        merged = _get(base, "/v1/cluster/slo")
        assert merged["unreachable"] == 0
        assert len(merged["servers"]) == 3
        for payload in merged["servers"].values():
            assert len(payload["objectives"]) >= 5
        dec = _get(base, "/v1/cluster/decisions?limit=64")
        assert len(dec["servers"]) == 3
        seqs = [r["seq"] for r in dec["decisions"]]
        assert len(seqs) == len(set(seqs)), "fan-in must dedup"
        assert any(
            r["site"] == "federation_retry"
            for r in dec["decisions"]
        )
        assert all(r.get("server") for r in dec["decisions"])
    finally:
        if http is not None:
            http.stop()
        cluster.stop()


# -- CLI + debug bundle ------------------------------------------------


def test_cli_slo_status_and_decisions(api, monkeypatch, capsys):
    from nomad_tpu.cli import main

    server, base = api
    monkeypatch.setenv("NOMAD_ADDR", base)
    DECISIONS.record(
        "adaptive_cap", "cap=48",
        inputs={"backlog": 12}, metrics=server.metrics,
    )
    main(["slo", "status"])
    out = capsys.readouterr().out
    assert "Worst:" in out
    assert "interactive_placement_p99" in out

    main(["slo", "status", "-json"])
    payload = json.loads(capsys.readouterr().out)
    assert len(payload["objectives"]) >= 5

    main(["decisions", "-site", "adaptive_cap"])
    out = capsys.readouterr().out
    assert "adaptive_cap" in out
    assert "cap=48" in out

    main(["decisions", "-json"])
    payload = json.loads(capsys.readouterr().out)
    assert payload["ring"]["depth"] >= 1


def test_debug_bundle_captures_slo_and_decisions(
    api, monkeypatch, tmp_path
):
    import tarfile

    from nomad_tpu.cli import main

    _, base = api
    monkeypatch.setenv("NOMAD_ADDR", base)
    out = tmp_path / "bundle.tar.gz"
    main(["operator", "debug", "-output", str(out)])
    with tarfile.open(out) as tar:
        names = tar.getnames()
    assert "nomad-debug/slo.json" in names
    assert "nomad-debug/decisions.json" in names
    assert "nomad-debug/cluster-slo.json" in names
    assert "nomad-debug/cluster-decisions.json" in names


# -- the engine wired to the real history ring ------------------------


def test_engine_reads_real_history_ring():
    """End-to-end against a real MetricsHistory: shed counters pushed
    through real snapshots drive the shed_rate objective from OK to
    BURNING."""
    m = Metrics()
    m.preregister(
        counters=("overload.shed", "overload.accepted"),
    )
    hist = MetricsHistory(m, windows=8, interval_s=60.0)
    engine = SLOEngine(m, hist)
    hist.snapshot_once()
    for _ in range(4):
        for _ in range(40):
            m.incr("overload.shed")
        for _ in range(60):
            m.incr("overload.accepted")
        hist.snapshot_once()
    st = engine.status()
    shed = _obj(st, "shed_rate")
    # 40% shed over a 5% budget = 8x burn in both windows
    assert shed["burn_fast"] == pytest.approx(8.0)
    assert shed["status"] == "BURNING"
    assert st["worst"] == "BURNING"
