"""Telemetry store tests: summary edge cases (max init, empty
snapshots), prometheus name-collision dedupe, exemplar plumbing, the
scrape endpoint format, and accessor behavior under concurrent
writers."""
import json
import threading
import urllib.request

from nomad_tpu.telemetry import Metrics, _Summary


# -- _Summary edges ---------------------------------------------------


def test_summary_max_tracks_all_negative_streams():
    """max started at 0.0, so an all-negative sample stream reported
    max=0.0 — a value that never occurred.  It must mirror min's
    sentinel idiom (-inf) and report the true maximum."""
    s = _Summary()
    for v in (-5.0, -2.5, -9.0):
        s.add(v)
    snap = s.snapshot()
    assert snap["max"] == -2.5
    assert snap["min"] == -9.0


def test_summary_empty_snapshot_guards_min_and_max():
    snap = _Summary().snapshot()
    assert snap["count"] == 0
    assert snap["min"] == 0.0
    assert snap["max"] == 0.0
    assert snap["exemplars"] == []


def test_summary_exemplars_link_p99_entries_to_traces():
    """The slow-tail ring entries surface their trace ids, slowest
    first, so a bad p99 links straight to /v1/traces/<id>."""
    s = _Summary()
    for i in range(100):
        s.add(float(i), exemplar=f"ev-{i}")
    s.add(500.0)  # slowest sample has NO exemplar: must be skipped
    snap = s.snapshot()
    ids = [e["trace_id"] for e in snap["exemplars"]]
    assert ids, snap
    assert ids[0] == "ev-99"
    assert all(e["value"] >= snap["p99"] for e in snap["exemplars"])
    assert len(ids) <= _Summary.EXEMPLARS


# -- prometheus_text --------------------------------------------------


def test_prometheus_text_dedupes_colliding_names():
    """esc() maps both '.' and '-' to '_': two distinct store names
    can collide into one scrape series, which Prometheus rejects.
    The first (sorted) name wins; the loser is skipped with a
    comment, never emitted twice."""
    m = Metrics()
    m.incr("replay.serial_fallbacks", 3)
    m.incr("replay-serial.fallbacks", 7)
    text = m.prometheus_text()
    sample_lines = [
        line
        for line in text.splitlines()
        if line.startswith("replay_serial_fallbacks ")
    ]
    assert len(sample_lines) == 1, text
    type_lines = [
        line
        for line in text.splitlines()
        if line.startswith("# TYPE replay_serial_fallbacks ")
    ]
    assert len(type_lines) == 1, text
    assert "# collision:" in text


def test_prometheus_text_dedupes_across_metric_kinds():
    """A gauge and a summary that escape to the same name must not
    both emit (TYPE redefinition breaks the scrape)."""
    m = Metrics()
    m.set_gauge("batch.launch", 1.0)
    m.add_sample("batch-launch", 2.0)
    text = m.prometheus_text()
    assert (
        sum(
            1
            for line in text.splitlines()
            if line.startswith("# TYPE batch_launch ")
        )
        == 1
    ), text


def test_prometheus_text_unique_names_all_emit():
    m = Metrics()
    m.incr("a.counter")
    m.set_gauge("a.gauge", 2.0)
    m.add_sample("a.sample", 3.0)
    text = m.prometheus_text()
    assert "# TYPE a_counter counter" in text
    assert "# TYPE a_gauge gauge" in text
    assert "# TYPE a_sample summary" in text
    assert "# collision:" not in text


# -- /v1/metrics?format=prometheus endpoint ---------------------------


def test_metrics_prometheus_endpoint_content_type_and_quantiles():
    from nomad_tpu.api import start_http_server
    from nomad_tpu.server import Server

    server = Server(num_schedulers=1, seed=1, batch_pipeline=False)
    server.start()
    http = start_http_server(server, port=0)
    try:
        server.metrics.incr("test.counter", 2)
        for v in (1.0, 2.0, 3.0):
            server.metrics.add_sample("test.sample", v)
        url = (
            f"http://127.0.0.1:{http.port}/v1/metrics"
            "?format=prometheus"
        )
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
            assert (
                resp.headers["Content-Type"]
                == "text/plain; version=0.0.4"
            )
            text = resp.read().decode()
        assert "# TYPE test_counter counter" in text
        assert "test_counter 2" in text
        assert "# TYPE test_sample summary" in text
        assert "test_sample_count 3" in text
        for q in ("0.5", "0.9", "0.99"):
            assert f'test_sample{{quantile="{q}"}}' in text, text
        # the JSON dump still works and carries exemplars per summary
        with urllib.request.urlopen(
            f"http://127.0.0.1:{http.port}/v1/metrics", timeout=10
        ) as resp:
            dump = json.loads(resp.read())
        assert "exemplars" in dump["samples"]["test.sample"]
    finally:
        http.stop()
        server.stop()


# -- accessors under concurrent writers -------------------------------


def test_get_counter_and_gauge_under_concurrent_writers():
    """get_counter/get_gauge race real writers: no exceptions, counter
    reads are monotonic, and the final values are exact."""
    m = Metrics()
    n_threads, n_incr = 4, 2000
    errors = []
    stop = threading.Event()

    def writer(i):
        for k in range(n_incr):
            m.incr("c.shared")
            m.set_gauge("g.shared", float(k))
            m.set_gauge(f"g.mine.{i}", float(k))

    def reader():
        last = 0.0
        while not stop.is_set():
            v = m.get_counter("c.shared")
            if v < last:
                errors.append(f"counter went backwards: {v} < {last}")
                return
            last = v
            g = m.get_gauge("g.shared")
            if g is not None and not (0.0 <= g < n_incr):
                errors.append(f"gauge out of range: {g}")
                return

    readers = [threading.Thread(target=reader) for _ in range(2)]
    writers = [
        threading.Thread(target=writer, args=(i,))
        for i in range(n_threads)
    ]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert not errors, errors
    assert m.get_counter("c.shared") == n_threads * n_incr
    assert m.get_gauge("g.shared") == float(n_incr - 1)
    assert m.get_gauge("g.never_set") is None
    assert m.get_counter("c.never_bumped") == 0.0
