"""Leadership-loss hardening: the batched hot path survives a revoke
at EVERY leadership-sensitive seam with zero lost evals and zero
double-commits, the plan applier rejects in-flight plans with
NotLeaderError, the broker's nack-timeout sweep covers drain_family's
shadow-heap members, and the explain/trace audit carries the
leadership generation.

The revoke points are forced deterministically through the chaos race
hooks (nomad_tpu/raft/chaos.py) — the same seams the cluster chaos
smoke exercises stochastically.
"""
import copy
import random
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.raft import NotLeaderError, chaos
from nomad_tpu.server import Server
from nomad_tpu.structs import compute_node_class


def make_nodes(n, seed=0):
    rng = random.Random(seed)
    nodes = []
    for _ in range(n):
        node = mock.node()
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.node_resources.memory_mb = rng.choice([8192, 16384])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    return nodes


def make_jobs(n, fam=None, cpu=500):
    jobs = []
    for i in range(n):
        job_id = (
            f"{fam}/dispatch-{i:04d}" if fam else f"lead-{i:04d}"
        )
        job = mock.job(id=job_id)
        job.type = "batch"
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].resources.cpu = cpu
        job.task_groups[0].tasks[0].resources.memory_mb = 256
        jobs.append(job)
    return jobs


def live_placements(server, job_id):
    return [
        (a.name, a.node_id)
        for a in server.store.allocs_by_job("default", job_id)
        if not a.terminal_status()
    ]


def settle(server, jobs, timeout=60.0):
    """Wait until every job is placed exactly once and every eval is
    terminal (the zero-lost / zero-double-commit acceptance)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        done = server.drain_to_idle(timeout=1.0) and all(
            len(live_placements(server, job.id)) == 1
            and all(
                e.terminal_status()
                for e in server.store.evals_by_job(
                    "default", job.id
                )
            )
            for job in jobs
        )
        if done:
            return True
        time.sleep(0.05)
    return False


@pytest.fixture(autouse=True)
def _clear_chaos_hooks():
    yield
    chaos.clear_hooks()


def arm_revoke_at(server, hook_name):
    """Arm a chaos hook that revokes leadership from a side thread the
    FIRST time the hot path crosses the named seam, and blocks the
    pipeline thread until the revoke is visible — a deterministic
    leadership-loss race at exactly that seam."""
    fired = threading.Event()
    revoked = threading.Event()

    def hook():
        if fired.is_set():
            return
        fired.set()

        def do_revoke():
            server.revoke_leadership()
            revoked.set()

        threading.Thread(target=do_revoke, daemon=True).start()
        deadline = time.monotonic() + 5.0
        while (
            time.monotonic() < deadline
            and server._leader_established
        ):
            time.sleep(0.001)

    chaos.install_hook(hook_name, hook)
    return fired, revoked


REVOKE_POINTS = [
    # (hook seam, env overrides) — gulp fill, mid-chunk-launch,
    # between speculate and commit, mid-storm-solve, storm staging
    ("gulp_filled", {}),
    ("chunk_launched", {}),
    ("pre_commit_wave", {}),
    ("storm_solved", {"NOMAD_TPU_STORM": "1", "NOMAD_TPU_STORM_MIN": "8"}),
    ("storm_staged", {"NOMAD_TPU_STORM": "1", "NOMAD_TPU_STORM_MIN": "8"}),
]


@pytest.mark.parametrize(
    "seam,env", REVOKE_POINTS, ids=[p[0] for p in REVOKE_POINTS]
)
def test_revoke_mid_flight_loses_nothing(monkeypatch, seam, env):
    """Leadership dies at the seam; after re-establishment every eval
    is redelivered and placed EXACTLY once — zero lost, zero
    double-commits — and the generation fence actually tripped."""
    for key, value in env.items():
        monkeypatch.setenv(key, value)
    storm = bool(env)
    fam = "leadfam" if storm else None
    jobs = make_jobs(24, fam=fam)
    server = Server(num_schedulers=1, seed=5, batch_pipeline=True)
    for node in make_nodes(16, seed=2):
        server.register_node(copy.deepcopy(node))
    # jobs land in the broker as one restore wave at establish (the
    # mass shape that keeps a chain/storm open long enough to kill)
    for job in jobs:
        server.register_job(copy.deepcopy(job))
    fired, revoked = arm_revoke_at(server, seam)
    server.start()
    try:
        assert fired.wait(30.0), f"seam {seam} never crossed"
        assert revoked.wait(10.0), "revoke did not complete"
        gen_before = server._leadership_gen
        assert not server._leader_established
        # nothing may be committed by the dead leadership after this
        # point; the broker flush unacked every outstanding token
        assert server.broker.unacked_count() == 0
        chaos.clear_hooks()
        # re-establish (the single-process analogue of the next
        # leader's election): restore_evals re-enqueues everything
        server.establish_leadership()
        assert server._leadership_gen == gen_before + 1
        assert settle(server, jobs, timeout=90.0), (
            "evals lost after revoke at " + seam
        )
        for job in jobs:
            assert len(live_placements(server, job.id)) == 1, (
                f"duplicate/missing placement for {job.id}"
            )
        m = server.metrics
        assert m.get_counter("leadership.revokes") >= 1.0
        assert m.get_counter("leadership.establishes") >= 2.0
    finally:
        chaos.clear_hooks()
        server.stop()


def test_revoke_mid_wave_generation_fence_trips(monkeypatch):
    """The acceptance race: leadership dies BETWEEN speculation and
    commit (forced via the pre_commit_wave fault hook) — the
    generation fence must trip and the wave must not commit."""
    jobs = make_jobs(16)
    server = Server(num_schedulers=1, seed=9, batch_pipeline=True)
    for node in make_nodes(12, seed=4):
        server.register_node(copy.deepcopy(node))
    for job in jobs:
        server.register_job(copy.deepcopy(job))
    fired, revoked = arm_revoke_at(server, "pre_commit_wave")
    server.start()
    try:
        assert fired.wait(30.0)
        assert revoked.wait(10.0)
        # the fence tripped (stale wave refused) and nothing the dead
        # leadership had in flight committed afterwards
        deadline = time.monotonic() + 10.0
        while (
            time.monotonic() < deadline
            and server.metrics.get_counter(
                "leadership.stale_wave_fenced"
            )
            < 1.0
        ):
            time.sleep(0.02)
        assert (
            server.metrics.get_counter("leadership.stale_wave_fenced")
            >= 1.0
        )
        placed_while_dead = sum(
            len(live_placements(server, job.id)) for job in jobs
        )
        committed_at_revoke = placed_while_dead
        time.sleep(0.5)  # give any straggler a chance to misbehave
        placed_later = sum(
            len(live_placements(server, job.id)) for job in jobs
        )
        assert placed_later == committed_at_revoke, (
            "a deposed leadership committed a wave member"
        )
        chaos.clear_hooks()
        server.establish_leadership()
        assert settle(server, jobs, timeout=90.0)
    finally:
        chaos.clear_hooks()
        server.stop()


def test_explain_and_trace_carry_leadership_generation():
    from nomad_tpu.explain import EXPLAIN
    from nomad_tpu.trace import TRACE

    jobs = make_jobs(6)
    server = Server(num_schedulers=1, seed=3, batch_pipeline=True)
    server.start()
    try:
        for node in make_nodes(8, seed=1):
            server.register_node(copy.deepcopy(node))
        for job in jobs:
            server.register_job(copy.deepcopy(job))
        assert server.drain_to_idle(30.0)
        gen = server._leadership_gen
        assert gen >= 1
        checked_explain = checked_trace = 0
        for job in jobs:
            for ev in server.store.evals_by_job("default", job.id):
                rec = EXPLAIN.get(ev.id)
                if rec is not None and "LeaderGen" in rec:
                    assert rec["LeaderGen"] == gen
                    checked_explain += 1
                trace = TRACE.get(ev.id)
                if trace is not None:
                    assert trace["attrs"].get("leader_gen") == gen
                    checked_trace += 1
        assert checked_explain > 0 and checked_trace > 0
    finally:
        server.stop()


def test_plan_applier_rejects_in_flight_plans_not_leader():
    """A plan staged while leadership is lost responds NotLeaderError
    (never a commit), and the plan queue refuses new plans."""
    from nomad_tpu.server.plan_apply import PlanApplier
    from nomad_tpu.server.plan_queue import PlanQueue
    from nomad_tpu.state.store import StateStore
    from nomad_tpu.structs import Plan

    is_leader = [True]
    queue = PlanQueue()
    queue.set_enabled(True)
    applier = PlanApplier(
        StateStore(), queue, leader_check=lambda: is_leader[0]
    )
    applier.start()
    try:
        is_leader[0] = False
        pending = queue.enqueue(Plan(eval_id="ev-x"))
        with pytest.raises(NotLeaderError):
            pending.wait(timeout=5.0)
    finally:
        applier.stop()
        queue.set_enabled(False)
    with pytest.raises(NotLeaderError):
        queue.enqueue(Plan(eval_id="ev-y"))


def test_broker_sweep_redelivers_crashed_storm_drain():
    """Satellite: drain_family's shadow-heap members must never rely
    on the storm path settling — a crashed _process_storm (simulated:
    leases taken, never acked/nacked) is fully redelivered by the
    nack-timeout sweep."""
    from nomad_tpu.server.eval_broker import EvalBroker, job_family
    from nomad_tpu.structs import Evaluation, new_id

    broker = EvalBroker(nack_timeout=0.1)
    broker.set_enabled(True)
    evs = [
        Evaluation(
            id=new_id(),
            namespace="default",
            job_id=f"fam/dispatch-{i:03d}",
            type="batch",
            priority=50,
        )
        for i in range(8)
    ]
    broker.enqueue_all(evs)
    ev, _token = broker.dequeue(["batch"], timeout=1.0)
    drained = broker.drain_family(
        ["batch"], job_family(ev), max_n=16
    )
    assert len(drained) == 7
    assert broker.unacked_count() == 8
    # the worker "crashed": nobody settles these leases.  Every
    # member must come back within the nack timeout.
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and (
        broker.unacked_count() or broker.ready_count() < 8
    ):
        time.sleep(0.02)
    assert broker.unacked_count() == 0
    assert broker.ready_count() == 8
    # redelivered members are the same evals, intact
    redelivered = set()
    while True:
        ev, token = broker.dequeue(["batch"], timeout=0.2)
        if ev is None:
            break
        redelivered.add(ev.id)
        broker.ack(ev.id, token)
    assert redelivered == {e.id for e in evs}


def test_broker_sweeper_rearmed_by_drain_after_thread_loss():
    """The sweep must not depend on set_enabled having started a
    healthy ticker: drain_family re-arms it."""
    from nomad_tpu.server.eval_broker import EvalBroker, job_family
    from nomad_tpu.structs import Evaluation, new_id

    broker = EvalBroker(nack_timeout=0.1)
    broker.set_enabled(True)
    # simulate a dead sweeper thread (e.g. killed by a runtime fault)
    dead = threading.Thread(target=lambda: None)
    dead.start()
    dead.join()
    with broker._lock:
        broker._ticker = dead
    evs = [
        Evaluation(
            id=new_id(),
            namespace="default",
            job_id=f"fam/dispatch-{i:03d}",
            type="batch",
            priority=50,
        )
        for i in range(4)
    ]
    broker.enqueue_all(evs)
    ev, _token = broker.dequeue(["batch"], timeout=1.0)
    broker.drain_family(["batch"], job_family(ev), max_n=8)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and broker.unacked_count():
        time.sleep(0.02)
    assert broker.unacked_count() == 0
    assert broker.ready_count() == 4


def test_revoke_unacks_outstanding_tokens_counter():
    server = Server(num_schedulers=0, batch_pipeline=False)
    server.start()
    try:
        job = make_jobs(1)[0]
        for node in make_nodes(2, seed=6):
            server.register_node(copy.deepcopy(node))
        server.register_job(job)
        ev, token = server.broker.dequeue(
            ["service", "batch", "system", "_core"], timeout=2.0
        )
        assert ev is not None
        assert server.broker.unacked_count() == 1
        server.revoke_leadership()
        assert server.broker.unacked_count() == 0
        assert (
            server.metrics.get_counter("leadership.unacked_on_revoke")
            >= 1.0
        )
        assert server.metrics.get_gauge("leadership.is_leader") == 0.0
    finally:
        server.stop()
