"""Test configuration: force the CPU backend with a virtual 8-device mesh
and 64-bit floats BEFORE jax is imported, so sharding tests run without
real multi-chip hardware and parity tests are bit-exact against the
float64 host oracle (SURVEY.md section 7.3)."""
import os

# hard-set (not setdefault): shells that export JAX_PLATFORMS=axon for
# the tunneled TPU must not leak into the test suite — the suite's
# parity contract is the x64 CPU backend with a virtual 8-device mesh
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
# cold kernel compiles block (instead of falling back to the
# sequential path while compiling in the background) so prescore-rate
# assertions are deterministic
os.environ["NOMAD_TPU_SYNC_COMPILE"] = "1"
# this sandbox's scheduler can park a timed wait far past its timeout;
# the broker's opt-in notify watchdog bounds the damage
os.environ["NOMAD_TPU_BROKER_WATCHDOG"] = "1"

# a TPU-tunnel sitecustomize may have already imported jax at
# interpreter start (before the env vars above took effect) and forced
# jax_platforms="axon,cpu" — force the config back via jax.config,
# which works post-import, or every kernel call in the suite silently
# targets the tunneled TPU (and hangs the suite when the tunnel drops)
# and, worse, runs f32 instead of the x64 the parity contract needs
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import random  # noqa: E402

import pytest  # noqa: E402

from nomad_tpu import mock  # noqa: E402
from nomad_tpu.sched.testing import Harness  # noqa: E402
from nomad_tpu.structs import compute_node_class  # noqa: E402


@pytest.fixture
def harness():
    return Harness()


def heterogeneous_cluster(
    harness: Harness,
    n_nodes: int,
    seed: int = 0,
    datacenters=("dc1", "dc2"),
    racks: int = 5,
):
    rng = random.Random(seed)
    nodes = []
    for _ in range(n_nodes):
        n = mock.node()
        n.node_resources.cpu = rng.choice([2000, 4000, 8000])
        n.node_resources.memory_mb = rng.choice([4096, 8192, 16384])
        n.datacenter = rng.choice(list(datacenters))
        n.attributes["rack"] = f"r{rng.randint(0, racks - 1)}"
        n.attributes["driver.docker"] = rng.choice(["1", "1", "1", "0"])
        n.attributes["os.version"] = rng.choice(["20.04", "22.04", "24.04"])
        n.computed_class = compute_node_class(n)
        harness.store.upsert_node(n)
        nodes.append(n)
    return nodes

