"""Million-node world seeding (nomad_tpu/loadgen/bigworld.py) and the
node table's coalescing dirty-row log.

Covers the O(dirty rows) contract the composed fan-out × pod topology
leans on: log compaction must be lossless for every "dirty since g"
query (bit-identity against an uncompacted reference), bulk columnar
registration must match the per-node upsert path, seeded allocation
ballast must replicate through the seed_world FSM command and survive
a snapshot round-trip, and the closed-form byte accounting of a
delta catch-up must hold.
"""
from __future__ import annotations

import numpy as np

from nomad_tpu.loadgen import bigworld
from nomad_tpu.server import fsm
from nomad_tpu.state import NodeTable, StateStore

SPEC = {"nodes": 300, "allocs": 3_000, "dcs": 2, "seed": 7, "prefix": "bwt"}


def _seeded_store(spec=None):
    store = StateStore()
    result = bigworld.seed_world(store, spec or SPEC)
    return store, result


# ---------------------------------------------------------------------
# dirty-row log: compaction bit-identity
# ---------------------------------------------------------------------


def test_compaction_is_lossless_for_every_dirty_since_query():
    """Coalescing keeps one entry per row (its latest generation);
    every ``usage_rows_dirty_since(g)`` answer must be identical to a
    full uncompacted reference log, before and after compaction."""
    table = NodeTable()
    nodes = bigworld.build_nodes(bigworld.normalize_spec(SPEC))[:48]
    for node in nodes:
        table.upsert_node(node)
    rng = np.random.default_rng(3)
    # reference log: every (generation, row) write ever made; start
    # from the upsert-time dirty marks
    ref = [(gen, row) for row, gen in table._usage_dirty.items()]
    # hammer a small row set so the log outgrows the dirty map and
    # auto-compaction actually triggers
    hot = [1, 3, 5, 7, 11]
    for _ in range(400):
        row = int(rng.choice(hot))
        node_id = table.node_ids[row]
        table.update_node_usage(node_id, (1.0, 2.0, 3.0))
        ref.append((table.usage_generation, row))
    assert table.usage_log_len() <= max(
        64, 2 * len(table._usage_dirty)
    ), "auto-compaction failed to bound the log"

    def reference_since(g):
        return {row for gen, row in ref if gen > g}

    gens = sorted({g for g, _ in ref} | {0, table.usage_generation})
    for g in gens:
        got = table.usage_rows_dirty_since(g)
        assert len(got) == len(set(got)), "duplicates survived"
        assert set(got) == reference_since(g), f"mismatch at gen {g}"
    # explicit compaction must not change a single answer
    table.compact_usage_log()
    assert table.usage_log_len() == len(table._usage_dirty)
    for g in gens:
        assert set(table.usage_rows_dirty_since(g)) == reference_since(
            g
        ), f"compaction changed the answer at gen {g}"


def test_dirty_log_length_stays_o_dirty_rows_under_rewrites():
    """A follower catching up over a million-row arena depends on the
    log being bounded by rows-currently-dirty, not writes-ever-made:
    rewriting the same row thousands of times must not grow it."""
    table = NodeTable()
    nodes = bigworld.build_nodes(bigworld.normalize_spec(SPEC))[:8]
    for node in nodes:
        table.upsert_node(node)
    nid = table.node_ids[0]
    for i in range(5_000):
        table.update_node_usage(nid, (float(i), 0.0, 0.0))
    assert table.usage_log_len() <= max(64, 2 * len(table._usage_dirty))
    assert len(table._usage_dirty) <= len(nodes)


# ---------------------------------------------------------------------
# bulk columnar registration vs per-node upsert
# ---------------------------------------------------------------------


def test_bulk_register_matches_per_node_upsert_columns():
    spec = bigworld.normalize_spec(SPEC)
    nodes = bigworld.build_nodes(spec)[:64]
    bulk, ref = NodeTable(), NodeTable()
    rows = bulk.bulk_register_nodes(nodes)
    for node in nodes:
        ref.upsert_node(node)
    assert list(rows) == [ref.row_of[n.id] for n in nodes]
    n = len(nodes)
    for col in (
        "active", "eligible",
        "cpu_total", "mem_total", "disk_total",
        "cpu_used", "mem_used", "disk_used",
    ):
        assert np.array_equal(
            getattr(bulk, col)[:n], getattr(ref, col)[:n]
        ), f"column {col} diverged"
    # every bulk row is usage-dirty under ONE generation so delta
    # mirrors pick the whole block up in a single catch-up query
    gens = {bulk._usage_dirty[r] for r in range(n)}
    assert gens == {bulk.usage_generation}
    assert set(bulk.usage_rows_dirty_since(0)) == set(range(n))


def test_store_bulk_register_is_one_index_bump():
    store = StateStore()
    before = store._index
    nodes = bigworld.build_nodes(bigworld.normalize_spec(SPEC))[:32]
    index = store.bulk_register_nodes(nodes)
    assert index == before + 1
    assert all(n.id in store.nodes for n in nodes)
    assert all(n.modify_index == index for n in nodes)


# ---------------------------------------------------------------------
# seed_world: determinism + ballast semantics
# ---------------------------------------------------------------------


def test_seed_world_is_deterministic_across_replicas():
    """The FSM command replays on every raft replica: two independent
    expansions of the same spec must agree bit-for-bit on the usage
    columns the placement kernels read."""
    a, ra = _seeded_store()
    b, rb = _seeded_store()
    assert ra["nodes"] == rb["nodes"] == SPEC["nodes"]
    assert ra["datacenters"] == rb["datacenters"]
    n = SPEC["nodes"]
    ta, tb = a.node_table, b.node_table
    for col in ("cpu_used", "mem_used", "disk_used", "cpu_total"):
        assert np.array_equal(
            getattr(ta, col)[:n], getattr(tb, col)[:n]
        ), f"replica divergence in {col}"
    assert a.seeded_alloc_count() == b.seeded_alloc_count() == SPEC["allocs"]


def test_seed_world_ballast_survives_usage_recompute():
    """Seeded ballast is a floor under real usage: recomputing a
    node's usage from its (zero) live allocs must keep the ballast."""
    store, _ = _seeded_store()
    table = store.node_table
    nid = table.node_ids[0]
    before = float(table.cpu_used[0])
    assert before > 0.0, "row 0 drew no ballast — pick a luckier seed"
    store.node_table.update_node_usage(
        nid, store._live_usage_for_node(nid)
    )
    assert float(table.cpu_used[0]) == before


def test_deleted_node_row_does_not_leak_ballast():
    """A freed row reused by a future join must not inherit the dead
    node's seeded allocation ballast."""
    store, _ = _seeded_store()
    table = store.node_table
    nid = table.node_ids[0]
    assert store._seed_usage is not None
    store.delete_node(nid)
    assert store._seed_usage[0][0] == 0.0
    assert store._seed_usage[1][0] == 0.0
    assert store._seed_usage[2][0] == 0.0


def test_usage_delta_since_covers_seeded_block():
    store, result = _seeded_store()
    gen, rows = store.usage_delta_since(0)
    assert gen == store.node_table.usage_generation
    start = result["row_start"]
    assert set(rows) >= set(range(start, start + SPEC["nodes"]))
    # a consumer synced at `gen` has nothing to catch up
    assert store.usage_delta_since(gen) == (gen, [])


def test_catchup_byte_closed_form():
    """The per-flush wire cost of a delta catch-up is the closed form
    the bigworld accounting reports: idx(int32) + 3 value columns
    (float64) over the dirty rows — O(dirty rows), independent of
    world size."""
    store, _ = _seeded_store()
    table = store.node_table
    gen0 = table.usage_generation
    k = 17
    for row in range(k):
        table.update_node_usage(
            table.node_ids[row], (5.0, 6.0, 7.0)
        )
    _, dirty = store.usage_delta_since(gen0)
    assert len(dirty) == k
    idx = np.asarray(dirty, dtype=np.int32)
    vals = [
        np.asarray(table.cpu_used[idx], dtype=np.float64),
        np.asarray(table.mem_used[idx], dtype=np.float64),
        np.asarray(table.disk_used[idx], dtype=np.float64),
    ]
    nbytes = idx.nbytes + sum(v.nbytes for v in vals)
    assert nbytes == k * 4 + 3 * k * 8


# ---------------------------------------------------------------------
# seed_world through the FSM: command + snapshot round-trip
# ---------------------------------------------------------------------


def test_seed_world_snapshot_round_trip_preserves_ballast():
    """Ballast is replicated state: a snapshot install on a fresh
    store must rebuild the same usage columns (re-rowed by node id)
    and the seeded alloc count."""
    store, _ = _seeded_store()
    payload = fsm.state_payload(store, None)
    assert payload["seed_alloc_count"] == SPEC["allocs"]
    fresh = StateStore()
    fsm.install_payload(fresh, None, payload)
    assert fresh.seeded_alloc_count() == SPEC["allocs"]
    src, dst = store.node_table, fresh.node_table
    for nid in list(store.nodes)[:50]:
        srow, drow = src.row_of[nid], dst.row_of[nid]
        for col in ("cpu_used", "mem_used", "disk_used"):
            assert getattr(src, col)[srow] == getattr(dst, col)[drow], (
                f"{col} diverged for {nid} after restore"
            )
    # the restored ballast keeps protecting the floor
    nid = dst.node_ids[0]
    before = float(dst.cpu_used[0])
    dst.update_node_usage(nid, fresh._live_usage_for_node(nid))
    assert float(dst.cpu_used[0]) == before


def test_seed_world_fsm_command_applies_on_replica():
    """The encoded command path a follower replays: decode + apply
    must seed the same world the leader expanded."""
    from nomad_tpu.server.fsm import ServerFSM

    store = StateStore()
    f = ServerFSM.__new__(ServerFSM)
    f.store = store
    result = f._apply_seed_world(SPEC)
    assert result["nodes"] == SPEC["nodes"]
    assert len(store.nodes) == SPEC["nodes"]
    assert store.seeded_alloc_count() == SPEC["allocs"]
