"""External Consul/Vault integration tests against fake local HTTP
servers (reference model: command/agent/consul/*_test.go uses a local
testutil consul; nomad/vault_test.go a mock vault).
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from nomad_tpu import mock
from nomad_tpu.external import (
    ConsulClient,
    ConsulSyncer,
    ExternalError,
    VaultClient,
    VaultSecretsProvider,
)


class _FakeConsul(BaseHTTPRequestHandler):
    services = {}

    def _reply(self, body=None, code=200):
        data = json.dumps(body).encode() if body is not None else b""
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_PUT(self):
        if self.path == "/v1/agent/service/register":
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length))
            type(self).services[payload["ID"]] = payload
            return self._reply({})
        if self.path.startswith("/v1/agent/service/deregister/"):
            sid = self.path.rsplit("/", 1)[1]
            type(self).services.pop(sid, None)
            return self._reply({})
        if self.path.startswith("/v1/kv/"):
            length = int(self.headers.get("Content-Length", 0))
            key = self.path[len("/v1/kv/"):]
            type(self).services.setdefault("_kv", {})[key] = (
                self.rfile.read(length).decode()
            )
            return self._reply(True)
        self._reply({}, 404)

    def do_GET(self):
        if self.path == "/v1/agent/services":
            return self._reply(type(self).services)
        if self.path.startswith("/v1/kv/"):
            key = self.path[len("/v1/kv/"):].split("?")[0]
            val = type(self).services.get("_kv", {}).get(key)
            if val is None:
                return self._reply(None, 404)
            return self._reply(val)
        self._reply({}, 404)

    def log_message(self, *a):
        pass


class _FakeVault(BaseHTTPRequestHandler):
    tokens = {}
    secrets = {"secret/web": {"user": "admin", "pass": "hunter2"}}
    revoked = []

    def _reply(self, body=None, code=200):
        data = json.dumps(body).encode() if body is not None else b""
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        payload = json.loads(self.rfile.read(length) or b"{}")
        if self.path == "/v1/auth/token/create":
            if self.headers.get("X-Vault-Token") != "root":
                return self._reply({"errors": ["permission denied"]}, 403)
            tok = f"s.child{len(type(self).tokens)}"
            type(self).tokens[tok] = payload
            return self._reply(
                {
                    "auth": {
                        "client_token": tok,
                        "policies": payload.get("policies", []),
                        "lease_duration": 3600,
                        "renewable": True,
                    }
                }
            )
        if self.path == "/v1/auth/token/renew-self":
            tok = self.headers.get("X-Vault-Token", "")
            if tok not in type(self).tokens:
                return self._reply({"errors": ["bad token"]}, 403)
            return self._reply(
                {"auth": {"client_token": tok, "lease_duration": 3600}}
            )
        if self.path == "/v1/auth/token/revoke":
            type(self).revoked.append(payload.get("token"))
            return self._reply({})
        self._reply({}, 404)

    def do_GET(self):
        path = self.path.lstrip("/").removeprefix("v1/")
        if path in type(self).secrets:
            return self._reply({"data": type(self).secrets[path]})
        self._reply({"errors": ["not found"]}, 404)

    def log_message(self, *a):
        pass


@pytest.fixture
def consul():
    _FakeConsul.services = {}
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeConsul)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


@pytest.fixture
def vault():
    _FakeVault.tokens = {}
    _FakeVault.revoked = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeVault)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def test_consul_register_deregister(consul):
    c = ConsulClient(consul)
    c.register_service(
        "svc-1", "web", address="10.0.0.1", port=8080, tags=["v1"]
    )
    assert "svc-1" in c.services()
    assert c.services()["svc-1"]["Port"] == 8080
    c.deregister_service("svc-1")
    assert "svc-1" not in c.services()


def test_consul_kv(consul):
    c = ConsulClient(consul)
    c.kv_put("app/config", "hello")
    assert c.kv_get("app/config") == "hello"
    assert c.kv_get("missing") is None


def test_consul_syncer_mirrors_catalog(consul):
    from nomad_tpu.server import Server

    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=3)
    try:
        syncer = ConsulSyncer(server.catalog, ConsulClient(consul))
        syncer.attach(server.store)

        node = mock.node()
        server.store.upsert_node(node)
        job = mock.job(id="websvc")
        from nomad_tpu.structs import Service

        job.task_groups[0].tasks[0].services = [
            Service(name="frontend", port_label="http")
        ]
        server.store.upsert_job(job)
        alloc = mock.alloc(node_id=node.id)
        alloc.job = job
        alloc.job_id = job.id
        alloc.client_status = "running"
        server.store.upsert_allocs([alloc])
        server.catalog.sync()
        syncer.sync()

        c = ConsulClient(consul)
        regs = c.services()
        assert any(
            v["Name"] == "frontend" for v in regs.values() if isinstance(v, dict) and "Name" in v
        ), regs

        # stopping the alloc deregisters on the next sync
        alloc.desired_status = "stop"
        alloc.client_status = "complete"
        server.store.upsert_allocs([alloc])
        server.catalog.sync()
        syncer.sync()
        regs = c.services()
        assert not any(
            isinstance(v, dict) and v.get("Name") == "frontend"
            for v in regs.values()
        )
    finally:
        server.stop()


def test_consul_syncer_retries_after_outage_without_alloc_change():
    """A register that fails during a Consul outage is retried by the
    periodic resync even on a quiet cluster (ADVICE r3: the external
    catalog must not stay stale until the next alloc event)."""
    import threading

    class _Inst:
        alloc_id = "a1"
        task = "t"
        service = "frontend"
        address = "10.0.0.1"
        port = 80
        tags = ()

    class _Catalog:
        def services(self):
            return ["frontend"]

        def instances(self, name):
            return [_Inst()]

    class _FlakyConsul:
        def __init__(self):
            self.down = True
            self.registered = {}
            self.synced = threading.Event()

        def register_service(self, sid, name, address, port, tags):
            if self.down:
                raise ExternalError("consul unreachable")
            self.registered[sid] = name
            self.synced.set()

        def deregister_service(self, sid):
            self.registered.pop(sid, None)

    consul = _FlakyConsul()
    syncer = ConsulSyncer(_Catalog(), consul)
    # first sync during the outage: fails, flags for retry
    syncer.sync()
    assert syncer._last_sync_failed and not consul.registered
    # consul recovers; NO alloc event fires — run the loop
    consul.down = False
    syncer._thread = threading.Thread(
        target=syncer._run, daemon=True
    )
    syncer._thread.start()
    try:
        assert consul.synced.wait(
            10.0
        ), "periodic resync must register after recovery"
        assert consul.registered
    finally:
        syncer.stop()


def test_vault_token_lifecycle(vault):
    v = VaultClient(vault, token="root")
    auth = v.derive_token(["web-policy"], metadata={"task": "t1"})
    assert auth["client_token"].startswith("s.child")
    assert auth["policies"] == ["web-policy"]

    renewed = v.renew_self(auth["client_token"])
    assert renewed["lease_duration"] == 3600

    v.revoke(auth["client_token"])
    assert auth["client_token"] in _FakeVault.revoked


def test_vault_derive_requires_valid_token(vault):
    v = VaultClient(vault, token="wrong")
    with pytest.raises(ExternalError):
        v.derive_token(["p"])


def test_vault_secrets_provider_renders_templates(vault):
    provider = VaultSecretsProvider(VaultClient(vault, token="root"))
    data = provider.read("secret/web")
    assert data == {"user": "admin", "pass": "hunter2"}
    assert provider.read("secret/missing") is None

    from nomad_tpu.client.templates import render_template

    out = render_template(
        'user={{ secret "secret/web" "user" }}', secrets=provider
    )
    assert out == "user=admin"
