"""Constraint operator semantics (reference feasible.go:750 and
feasible_test.go TestCheckConstraint/TestCheckVersionConstraint ...).
"""
from nomad_tpu.sched.operators import (
    check_constraint,
    check_version_constraint,
)


def chk(op, l, r, lf=True, rf=True):
    return check_constraint(op, l, r, lf, rf)


def test_equality():
    assert chk("=", "foo", "foo")
    assert not chk("=", "foo", "bar")
    assert chk("==", "a", "a")
    assert chk("is", "a", "a")
    assert not chk("=", None, "a", lf=False)


def test_inequality():
    assert chk("!=", "a", "b")
    assert not chk("!=", "a", "a")
    # missing value != present value
    assert chk("!=", None, "a", lf=False)
    # both missing are equal
    assert not chk("!=", None, None, lf=False, rf=False)


def test_lexical_order():
    assert chk("<", "abc", "abd")
    assert chk("<=", "abc", "abc")
    assert chk(">", "b", "a")
    assert not chk(">", "a", "b")


def test_is_set():
    assert chk("is_set", "anything", None, rf=False)
    assert not chk("is_set", None, None, lf=False, rf=False)
    assert chk("is_not_set", None, None, lf=False, rf=False)
    assert not chk("is_not_set", "x", None, rf=False)


def test_version():
    assert chk("version", "1.2.3", ">= 1.0, < 2.0")
    assert not chk("version", "2.1.0", ">= 1.0, < 2.0")
    assert chk("version", "0.13.0", "> 0.12")
    assert chk("version", "1.7.0-beta", "< 1.7.0")
    assert not chk("version", "banana", "> 1.0")
    assert not chk("version", "1.0", "banana")


def test_version_pessimistic():
    assert check_version_constraint("1.2.5", "~> 1.2")
    assert check_version_constraint("1.2.5", "~> 1.2.3")
    assert not check_version_constraint("1.3.0", "~> 1.2.3")
    assert not check_version_constraint("2.0.0", "~> 1.2")


def test_semver():
    assert chk("semver", "1.2.3", ">= 1.0.0")
    assert not chk("semver", "0.9.0", ">= 1.0.0")


def test_regexp():
    assert chk("regexp", "linux-x64", "linux")
    assert chk("regexp", "ubuntu-20.04", r"2[02]\.04")
    assert not chk("regexp", "darwin", "linux")
    # bad pattern fails closed
    assert not chk("regexp", "x", "(unclosed")


def test_set_contains():
    assert chk("set_contains", "a,b,c", "a,c")
    assert not chk("set_contains", "a,b", "a,z")
    assert chk("set_contains_all", "a, b, c", "b")
    assert chk("set_contains_any", "a,b", "z,b")
    assert not chk("set_contains_any", "a,b", "z,y")


def test_distinct_operators_pass_through():
    assert chk("distinct_hosts", None, None, lf=False, rf=False)
    assert chk("distinct_property", "x", "2")
