"""Flowgraph core + whole-program concurrency rules (tier-1).

Covers the cross-module analysis layer the shared-state-guard /
blocking-while-locked / kernel-contract / concurrency-doc rules ride
on: thread-entry discovery (including virtual dispatch, callback
registration and lifecycle pseudo-entries), guaranteed-held lock
dataflow on the synthetic two-thread fixture, entry conflict
semantics, the astutil conditional-stage-key edge cases, the
``--files`` narrowing contract for cross-file rules, the
stale-suppression finding, and the ``--json`` finding schema
downstream tooling consumes.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.nomadlint import Context, run  # noqa: E402
from tools.nomadlint.flowgraph import (  # noqa: E402
    Entry,
    build_flowgraph,
    entries_conflict,
)

FIXTURES = os.path.join(
    REPO, "tools", "nomadlint", "fixtures"
)


def _ctx(**overrides):
    return Context(REPO, overrides or None)


def _fixture_ctx(sub, name):
    return Context(
        REPO,
        {"scan_files": [os.path.join(FIXTURES, sub, name)]},
    )


# ---------------------------------------------------------------------------
# flowgraph core on the synthetic two-thread fixture
# ---------------------------------------------------------------------------


def test_fixture_entries_and_guards():
    g = build_flowgraph(_fixture_ctx("shared_state", "bad.py"))
    entry_methods = {e.method for e in g.entries}
    assert "Thing._loop" in entry_methods
    assert "Thing._poker" in entry_methods
    # guarded: every access site holds the one lock
    guarded = g.shared_access[("Thing", "guarded")]
    assert guarded
    assert all(s.guards for s in guarded)
    common = set.intersection(*(set(s.guards) for s in guarded))
    assert common
    # racy: the loop thread's increment holds nothing
    racy = g.shared_access[("Thing", "racy")]
    assert any(not s.guards and s.kind == "w" for s in racy)


def test_fixture_two_thread_entries_have_distinct_groups():
    g = build_flowgraph(_fixture_ctx("shared_state", "bad.py"))
    loop = next(e for e in g.entries if e.method == "Thing._loop")
    poker = next(
        e for e in g.entries if e.method == "Thing._poker"
    )
    assert loop.group != poker.group
    assert entries_conflict(loop, poker)


def test_entry_conflict_semantics():
    a = Entry("thread:A.run", "A.run", "thread", "x.py:1",
              None, group="x.py:1", multi=False)
    b = Entry("thread:B.run", "B.run", "thread", "x.py:1",
              None, group="x.py:1", multi=False)
    c = Entry("http:H.do_GET", "H.do_GET", "http", "h.py:1",
              None, group="http:H.do_GET", multi=True)
    # virtual-dispatch siblings of one spawn never race on one self
    assert not entries_conflict(a, b)
    # an HTTP handler overlaps itself (ThreadingHTTPServer)
    assert entries_conflict(c, c)
    assert entries_conflict(a, c)


def test_live_flowgraph_discovers_known_entries_and_locks():
    g = build_flowgraph(_ctx())
    methods = {e.method for e in g.entries}
    # spawn discovery: worker thread, probe thread, broker sweeper,
    # pool dispatch, nested compile closure, HTTP dispatch
    assert "BatchWorker.run" in methods
    assert "DeviceSupervisor._probe_loop" in methods
    assert "EvalBroker._tick" in methods
    assert "BatchWorker._speculate_one" in methods
    assert (
        "BatchWorker._launch_ready.<compile_in_background>"
        in methods
    )
    assert "APIHandler.do_GET" in methods
    # callback registration: the supervisor invokes these on its
    # probe thread / the tripping worker thread
    assert "BatchWorker._on_device_transition" in methods
    assert "BatchWorker.warm_shapes" in methods
    # lifecycle pseudo-entries (the operator thread)
    assert "Server.stop" in methods
    # lock table speaks the lock-discipline vocabulary
    assert (
        "batch_worker.py:BatchWorker._usage_cache_lock" in g.locks
    )
    assert "store.py:StateStore._lock" in g.locks
    assert g.locks["store.py:StateStore._lock"]  # RLock


def test_condition_canonicalizes_to_wrapped_lock():
    g = build_flowgraph(_ctx())
    # StateStore._watch_cond = threading.Condition(self._lock):
    # holding the condition IS holding the lock — one key, not two
    assert "store.py:StateStore._watch_cond" not in g.locks


def test_guaranteed_held_intersection():
    """A method called both with and without a lock held must not
    count the lock as a guaranteed guard."""
    fix = os.path.join(FIXTURES, "shared_state", "bad.py")
    g = build_flowgraph(
        Context(REPO, {"scan_files": [fix]})
    )
    # _poker reads racy with NO guard even though _loop's guarded
    # access holds the lock — per-site facts stay separate
    racy_sites = g.shared_access[("Thing", "racy")]
    by_kind = {(s.kind, bool(s.guards)) for s in racy_sites}
    assert ("w", False) in by_kind


# ---------------------------------------------------------------------------
# concurrency rules over the fixtures
# ---------------------------------------------------------------------------


def test_shared_state_rule_names_both_sites_and_entries():
    from tools.nomadlint.rules.concurrency import (
        SharedStateGuardRule,
    )

    findings = SharedStateGuardRule().check(
        _fixture_ctx("shared_state", "bad.py")
    )
    assert len(findings) == 1
    msg = findings[0].message
    assert "Thing.racy" in msg
    assert "Thing._loop" in msg and "Thing._poker" in msg
    assert "no common lock" in msg


def test_blocking_rule_direct_transitive_and_event_wait():
    from tools.nomadlint.rules.concurrency import (
        BlockingWhileLockedRule,
    )

    findings = BlockingWhileLockedRule().check(
        _fixture_ctx("blocking", "bad.py")
    )
    msgs = "\n".join(f.message for f in findings)
    assert "time.sleep()" in msgs
    assert "device_get" in msgs  # two frames down
    assert "_stop.wait()" in msgs  # Event wait under a lock
    clean = BlockingWhileLockedRule().check(
        _fixture_ctx("blocking", "clean.py")
    )
    assert clean == []  # Condition.wait under its own lock exempt


def test_shared_state_allowlist_entries_all_live():
    """Every SHARED_STATE_ALLOWLIST entry must match a live race
    pair (the rule reports stale entries as findings on full
    runs)."""
    from tools.nomadlint.rules.concurrency import (
        SharedStateGuardRule,
    )

    findings = SharedStateGuardRule().check(_ctx())
    assert findings == [], [f.message for f in findings]


# ---------------------------------------------------------------------------
# astutil conditional-stage-key edge cases
# ---------------------------------------------------------------------------


def _parse(src):
    import ast

    return ast.parse(src)


def test_expr_strings_nested_ternary():
    import ast

    from tools.nomadlint.astutil import expr_strings

    expr = ast.parse(
        '"a" if x else ("b" if y else "c")', mode="eval"
    ).body
    assert expr_strings(expr) == {"a", "b", "c"}


def test_literal_env_reassigned_across_branches():
    from tools.nomadlint.astutil import literal_env

    tree = _parse(
        "if cond:\n"
        '    stage = "mesh_launch"\n'
        "else:\n"
        '    stage = "launch" if warm else "fetch"\n'
        'stage = "storm_solve"\n'
    )
    env = literal_env(tree)
    # module-wide union: every branch's binding is a possible value
    assert env["stage"] == {
        "mesh_launch", "launch", "fetch", "storm_solve",
    }


def test_observed_keys_through_conditional_local():
    from tools.nomadlint.astutil import observed_keys

    tree = _parse(
        "class W:\n"
        "    def go(self, mesh):\n"
        '        key = "mesh_launch" if mesh else "launch"\n'
        "        self._observe(key, 1.0)\n"
        '        self._observe("fetch" if mesh else "launch", 2.0)\n'
    )
    assert observed_keys(tree) == {
        "mesh_launch", "launch", "fetch",
    }


def test_span_names_through_observe_chunk_conditional():
    from tools.nomadlint.astutil import span_names_used

    tree = _parse(
        "class W:\n"
        "    def go(self, mesh):\n"
        '        stage = "mesh_launch" if mesh else "launch"\n'
        "        self._observe_chunk(stage, 0, [])\n"
    )
    assert span_names_used(tree) == {
        "batch_worker.mesh_launch", "batch_worker.launch",
    }


# ---------------------------------------------------------------------------
# --files narrowing contract + stale suppressions
# ---------------------------------------------------------------------------


def test_narrowed_run_still_runs_cross_file_rules_fully():
    """config-drift's dead-registry direction (4) needs the full
    usage scan: a --files run must not skip it (declared file
    dependencies override narrowing)."""
    result = run(
        _ctx(
            narrow_files=[
                os.path.join(REPO, "nomad_tpu", "envknobs.py")
            ]
        ),
        ["config-drift"],
    )
    assert result.ok  # full scan ran: no false dead-row findings


def test_narrowed_run_restricts_per_file_rules():
    import tempfile

    bad = (
        "import jax\n"
        "def make():\n"
        "    return jax.jit(lambda x: x, donate_argnums=(0,))\n"
        "def use(a):\n"
        "    f = make()\n"
        "    out = f(a)\n"
        "    return a + out\n"
    )
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "bad_donate.py")
        with open(path, "w") as fh:
            fh.write(bad)
        result = run(
            _ctx(narrow_files=[path]), ["donation-safety"]
        )
    assert not result.ok
    assert result.findings[0].rule == "donation-safety"


def test_stale_suppression_is_a_finding(tmp_path):
    """A justified suppression that hides nothing is itself a
    finding on a full-rule run (and the live tree has none)."""
    stale = tmp_path / "stale.py"
    stale.write_text(
        "# nomadlint: disable=donation-safety -- justified once\n"
        "x = 1\n"
    )
    result = run(_ctx(scan_files=[str(stale)]))
    hits = [
        f
        for f in result.findings
        if f.rule == "stale-suppression"
        and f.path == str(stale)
    ]
    assert len(hits) == 1
    assert hits[0].line == 1
    # narrowed (--files) runs must NOT report stale suppressions:
    # the rule that would have matched may not have seen its file
    narrowed = run(
        _ctx(
            scan_files=[str(stale)],
            narrow_files=[str(stale)],
        )
    )
    assert not [
        f
        for f in narrowed.findings
        if f.rule == "stale-suppression"
    ]
    # the live tree carries no stale suppressions
    full = run(_ctx())
    assert not [
        f for f in full.findings if f.rule == "stale-suppression"
    ]
    assert full.ok


# ---------------------------------------------------------------------------
# --json schema (downstream tooling contract)
# ---------------------------------------------------------------------------


def test_json_finding_schema():
    out = subprocess.run(
        [sys.executable, "-m", "tools.nomadlint", "--json"],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    payload = json.loads(out.stdout)
    assert set(payload) == {
        "ok", "rules_run", "findings", "suppressed",
    }
    assert payload["ok"] is True
    assert isinstance(payload["rules_run"], list)
    assert all(isinstance(r, str) for r in payload["rules_run"])
    assert len(payload["rules_run"]) >= 20
    for entry in payload["findings"] + payload["suppressed"]:
        assert set(entry) == {"rule", "path", "line", "message"}
        assert isinstance(entry["rule"], str)
        assert isinstance(entry["path"], str)
        assert not os.path.isabs(entry["path"])  # repo-relative
        assert isinstance(entry["line"], int)
        assert isinstance(entry["message"], str)
    # the three live suppressions ride along machine-readably
    sup_rules = {e["rule"] for e in payload["suppressed"]}
    assert "donation-safety" in sup_rules
    assert "jit-purity" in sup_rules
    assert "blocking-while-locked" in sup_rules


def test_dump_flowgraph_cli():
    out = subprocess.run(
        [
            sys.executable, "-m", "tools.nomadlint",
            "--dump-flowgraph",
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert out.returncode == 0
    assert "**Thread entries**" in out.stdout
    assert "BatchWorker.run" in out.stdout
    assert "**Locks**" in out.stdout
    assert "_usage_cache_lock" in out.stdout


# ---------------------------------------------------------------------------
# kernel-contract specifics beyond the generic fixture round-trip
# ---------------------------------------------------------------------------


def test_kernel_contract_ladder_drift_detected(tmp_path):
    from tools.nomadlint.rules.kernel_contract import (
        KernelContractRule,
    )

    rule = KernelContractRule()
    ctx = rule._mutated(
        _ctx(), str(tmp_path), "batch_worker",
        old="CHUNK_BUCKETS = (2, 4, 8)",
        new="CHUNK_BUCKETS = (2, 4)",
    )
    findings = rule.check(ctx)
    assert any("drifted" in f.message for f in findings)


def test_kernel_contract_live_ladders_green():
    from nomad_tpu.ops.contracts import check_contracts

    assert check_contracts() == []
