"""Wire protocol + native bridge tests.

Builds native/libnomadwire.so with g++ (skipped if unavailable) and
verifies: codec roundtrips, byte-identical encoding between the C++ and
Python codecs, and an end-to-end RPC through the native bridge into the
TPU scheduler service.
"""
import json
import socket
import subprocess

import pytest

from nomad_tpu import mock, wire
from nomad_tpu.server import Server
from nomad_tpu.server.bridge_service import BridgeService

NATIVE_DIR = wire._NATIVE_PATH.rsplit("/", 1)[0]


@pytest.fixture(scope="module")
def native():
    try:
        subprocess.run(
            ["make", "-C", NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.CalledProcessError) as exc:
        pytest.skip(f"native toolchain unavailable: {exc}")
    return wire.NativeWire()


SAMPLES = [
    None,
    True,
    False,
    0,
    -1,
    2**40,
    -(2**40),
    3.5,
    -0.125,
    "",
    "hello",
    "uniçode ☃",
    [],
    [1, 2, 3],
    {"a": 1, "b": [True, None, "x"], "c": {"nested": 2.5}},
    {"evals": [{"eval_id": "e1", "count": 3, "cpu": 500}]},
]


@pytest.mark.parametrize("value", SAMPLES)
def test_python_codec_roundtrip(value):
    assert wire.decode(wire.encode(value)) == value


def test_python_codec_bytes():
    assert wire.decode(wire.encode(b"\x00\xff")) == b"\x00\xff"


@pytest.mark.parametrize("value", SAMPLES)
def test_native_codec_matches_python(native, value):
    encoded_cpp = native.encode_json(value)
    encoded_py = wire.encode(value)
    assert encoded_cpp == encoded_py, (
        f"codec divergence for {value!r}:\n"
        f" cpp={encoded_cpp.hex()}\n py={encoded_py.hex()}"
    )
    assert native.decode_json(encoded_py) == value


def test_native_version(native):
    assert native.version().startswith("nomad-tpu-wire/")


@pytest.fixture
def bridge():
    server = Server(num_schedulers=0, seed=55)
    server.start()
    for _ in range(10):
        server.register_node(mock.node())
    service = BridgeService(server, port=0)
    service.start()
    yield server, service
    service.stop()
    server.stop()


def test_bridge_ping_python_client(bridge):
    _server, service = bridge
    sock = socket.create_connection(("127.0.0.1", service.port))
    try:
        resp = wire.call(sock, "TPUScheduler.Ping", {})
        assert resp["ok"] is True
        assert resp["nodes"] == 10
    finally:
        sock.close()


def test_bridge_score_batch_python_client(bridge):
    _server, service = bridge
    sock = socket.create_connection(("127.0.0.1", service.port))
    try:
        resp = wire.call(
            sock,
            "TPUScheduler.ScoreBatch",
            {
                "evals": [
                    {"eval_id": "e1", "seed": 7, "count": 3,
                     "cpu": 500, "memory_mb": 256},
                    {"eval_id": "e2", "seed": 8, "count": 2,
                     "cpu": 200, "memory_mb": 128},
                ]
            },
        )
    finally:
        sock.close()
    results = {r["eval_id"]: r["nodes"] for r in resp["results"]}
    assert len(results["e1"]) == 3
    assert len(results["e2"]) == 2
    # anti-affinity spreads one eval's picks over distinct nodes
    assert len(set(results["e1"])) == 3


def test_bridge_end_to_end_native_client(native, bridge):
    """The full seam: C++ shim -> framed wire -> Python service ->
    batched kernel -> C++ -> caller."""
    _server, service = bridge
    fd = native.connect("127.0.0.1", service.port)
    try:
        resp = native.call_json(fd, "TPUScheduler.Ping", {})
        assert resp["ok"] is True
        resp = native.call_json(
            fd,
            "TPUScheduler.ScoreBatch",
            {
                "evals": [
                    {"eval_id": "native-1", "seed": 3, "count": 4,
                     "cpu": 300, "memory_mb": 128}
                ]
            },
        )
        assert len(resp["results"][0]["nodes"]) == 4
    finally:
        native.close(fd)


def test_bridge_unknown_method(bridge):
    _server, service = bridge
    sock = socket.create_connection(("127.0.0.1", service.port))
    try:
        resp = wire.call(sock, "Nope.Nope", {})
        assert "error" in resp
    finally:
        sock.close()
