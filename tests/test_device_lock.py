"""Cross-process accelerator lock (nomad_tpu/device_lock.py).

A second jax process against the tunneled single-chip TPU wedges the
session (that is how round 3 lost its benchmark).  The lock makes the
second process block/abort instead."""
import os
import subprocess
import sys

from nomad_tpu import device_lock


def test_cpu_only_skips_lock(monkeypatch, tmp_path):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv(
        "NOMAD_TPU_DEVICE_LOCK", str(tmp_path / "lock")
    )
    assert device_lock.ensure_device_lock("test")
    # no lockfile created — CPU backends are not exclusive
    assert not (tmp_path / "lock").exists()


def test_unset_platform_skips_lock(monkeypatch, tmp_path):
    """No JAX_PLATFORMS means no tunneled accelerator is declared: a
    server + client sharing a CPU-only box must not serialize on (or
    deadlock over) a process-lifetime lock."""
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv(
        "NOMAD_TPU_DEVICE_LOCK", str(tmp_path / "lock")
    )
    assert device_lock.ensure_device_lock("test")
    assert not (tmp_path / "lock").exists()


def test_bounded_caller_wait_overrides_default(monkeypatch, tmp_path):
    """A caller-supplied wait (the fingerprint's enumeration deadline)
    bounds the acquire even when the env default would block forever."""
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")
    path = tmp_path / "lock"
    monkeypatch.setenv("NOMAD_TPU_DEVICE_LOCK", str(path))
    import fcntl

    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o666)
    fcntl.flock(fd, fcntl.LOCK_EX)
    try:
        import time

        t0 = time.monotonic()
        assert not device_lock.ensure_device_lock(
            "fingerprint", wait_s=1.0
        )
        assert time.monotonic() - t0 < 10.0
    finally:
        os.close(fd)


def test_lock_acquire_and_idempotent(monkeypatch, tmp_path):
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")
    path = tmp_path / "lock"
    monkeypatch.setenv("NOMAD_TPU_DEVICE_LOCK", str(path))
    try:
        assert device_lock.ensure_device_lock("first")
        assert device_lock.ensure_device_lock("again")
        assert path.exists()
        assert f"pid={os.getpid()}" in path.read_text()
    finally:
        device_lock.release_device_lock()


def test_second_process_blocks_until_timeout(monkeypatch, tmp_path):
    """While this process holds the lock, a second process with a
    bounded wait must fail to acquire it (rather than proceeding into
    backend init)."""
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")
    path = tmp_path / "lock"
    monkeypatch.setenv("NOMAD_TPU_DEVICE_LOCK", str(path))
    try:
        assert device_lock.ensure_device_lock("holder")
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="axon,cpu",
            NOMAD_TPU_DEVICE_LOCK=str(path),
            NOMAD_TPU_DEVICE_LOCK_WAIT="1.5",
        )
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import sys; sys.path.insert(0, %r); "
                "from nomad_tpu.device_lock import ensure_device_lock; "
                "sys.exit(0 if not ensure_device_lock('second') else 1)"
                % os.path.dirname(
                    os.path.dirname(device_lock.__file__)
                ),
            ],
            env=env,
            timeout=30,
            capture_output=True,
        )
        assert proc.returncode == 0, proc.stderr.decode()
    finally:
        device_lock.release_device_lock()


def test_released_lock_is_acquirable(monkeypatch, tmp_path):
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")
    path = tmp_path / "lock"
    monkeypatch.setenv("NOMAD_TPU_DEVICE_LOCK", str(path))
    assert device_lock.ensure_device_lock("a")
    device_lock.release_device_lock()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="axon,cpu",
        NOMAD_TPU_DEVICE_LOCK=str(path),
        NOMAD_TPU_DEVICE_LOCK_WAIT="5",
    )
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sys; sys.path.insert(0, %r); "
            "from nomad_tpu.device_lock import ensure_device_lock; "
            "sys.exit(0 if ensure_device_lock('free') else 1)"
            % os.path.dirname(os.path.dirname(device_lock.__file__)),
        ],
        env=env,
        timeout=30,
        capture_output=True,
    )
    assert proc.returncode == 0, proc.stderr.decode()
