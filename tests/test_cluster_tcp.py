"""Cross-process control plane over framed TCP.

VERDICT r2 item 3: until two processes form a cluster and fail over,
the multi-server control plane is a simulation.  These tests cover the
networked stack at three levels:

1. the TcpTransport itself (framing, typed error envelopes),
2. an in-process 3-server cluster whose raft/gossip/forwarding all
   travel over real sockets,
3. three separate OS processes (`python -m nomad_tpu.server.netagent`)
   that boot, elect, replicate an HTTP write submitted to a follower,
   survive a SIGKILL of the leader, and elect a new one.

Reference shape: nomad/raft_rpc.go (raft over the server port),
nomad/rpc.go:335 (multiplexed connections), rpc.go:509 (leader
forwarding), nomad/testing.go:44 + TestJoin (cluster boots in tests).
"""
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.api.codec import job_to_dict
from nomad_tpu.raft.node import NotLeaderError
from nomad_tpu.raft.tcp import TcpTransport
from nomad_tpu.raft.transport import TransportError


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# transport unit tests
# ---------------------------------------------------------------------------


def test_tcp_transport_roundtrip_and_concurrency():
    transport = TcpTransport()
    addr = f"127.0.0.1:{free_port()}"

    def handler(method, payload):
        if method == "echo":
            return {"you_sent": payload, "method": method}
        raise ValueError(f"unknown {method}")

    transport.register(addr, handler)
    try:
        out = transport.rpc(
            "client", addr, "echo",
            {"n": 7, "blob": b"\x00\x01", "nested": {"a": [1, 2]}},
        )
        assert out["you_sent"]["n"] == 7
        assert out["you_sent"]["blob"] == b"\x00\x01"
        assert out["you_sent"]["nested"]["a"] == [1, 2]

        # concurrent calls from multiple threads share the pool safely
        import threading

        errs = []

        def worker(i):
            try:
                r = transport.rpc("c", addr, "echo", {"i": i})
                assert r["you_sent"]["i"] == i
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
    finally:
        transport.close()


def test_tcp_transport_typed_errors():
    transport = TcpTransport()
    addr = f"127.0.0.1:{free_port()}"

    def handler(method, payload):
        if method == "not_leader":
            raise NotLeaderError("10.0.0.9:4647")
        if method == "value":
            raise ValueError("bad input")
        raise RuntimeError("boom")

    transport.register(addr, handler)
    try:
        with pytest.raises(NotLeaderError) as exc_info:
            transport.rpc("c", addr, "not_leader", {})
        assert exc_info.value.leader == "10.0.0.9:4647"
        with pytest.raises(ValueError, match="bad input"):
            transport.rpc("c", addr, "value", {})
        with pytest.raises(RuntimeError, match="boom"):
            transport.rpc("c", addr, "other", {})
    finally:
        transport.close()


def test_tcp_transport_unreachable_fails_fast():
    transport = TcpTransport()
    dead = f"127.0.0.1:{free_port()}"  # nothing listening
    t0 = time.monotonic()
    with pytest.raises(TransportError):
        transport.rpc("c", dead, "x", {})
    first = time.monotonic() - t0
    # breaker: the second call fails immediately
    t0 = time.monotonic()
    with pytest.raises(TransportError):
        transport.rpc("c", dead, "x", {})
    second = time.monotonic() - t0
    assert first < 2.0
    assert second < 0.05
    transport.close()


# ---------------------------------------------------------------------------
# in-process cluster over real sockets
# ---------------------------------------------------------------------------


def test_tcp_cluster_elects_forwards_and_fails_over():
    from nomad_tpu.server.cluster import ClusterServer

    addrs = [f"127.0.0.1:{free_port()}" for _ in range(3)]
    transports = [TcpTransport() for _ in range(3)]
    servers = [
        ClusterServer(
            addr,
            addrs,
            transports[i],
            election_timeout=0.6,
            heartbeat_interval=0.15,
        )
        for i, addr in enumerate(addrs)
    ]
    try:
        for s in servers:
            s.start()
        for s in servers[1:]:
            s.join(addrs[0])

        leader = _wait_leader(servers)
        followers = [s for s in servers if s is not leader]

        # node + job registered THROUGH A FOLLOWER forward to the
        # leader and replicate everywhere
        node = mock.node()
        followers[0].register_node(node)
        job = mock.job(id="tcp-job")
        followers[1].register_job(job)
        _wait_for(
            lambda: leader.store.allocs_by_job("default", "tcp-job"),
            "allocs placed via follower-submitted job",
        )
        for s in servers:
            _wait_for(
                lambda s=s: s.store.job_by_id("default", "tcp-job")
                is not None
                and s.store.allocs_by_job("default", "tcp-job"),
                f"replication to {s.addr}",
            )

        # kill the leader process-style (no graceful leave)
        leader.raft.stop()
        leader.revoke_leadership()
        survivors = followers
        new_leader = _wait_leader(survivors, timeout=15)
        assert new_leader is not leader

        # writes keep working through the remaining follower
        other = [s for s in survivors if s is not new_leader][0]
        job2 = mock.job(id="tcp-job-2")
        other.register_job(job2)
        for s in survivors:
            _wait_for(
                lambda s=s: s.store.job_by_id("default", "tcp-job-2")
                is not None,
                f"post-failover replication to {s.addr}",
            )
    finally:
        for s in servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001
                pass
        for t in transports:
            t.close()


def _wait_leader(servers, timeout=15):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [
            s
            for s in servers
            if s.is_leader() and s._leader_established
        ]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.05)
    raise AssertionError("no established leader over TCP")


# ---------------------------------------------------------------------------
# three real OS processes
# ---------------------------------------------------------------------------


def _http_get(port, path, timeout=10.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as resp:
        return json.loads(resp.read().decode())


def _http_post(port, path, payload, timeout=15.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


@pytest.mark.slow
def test_three_process_cluster_failover():
    rpc_ports = [free_port() for _ in range(3)]
    http_ports = [free_port() for _ in range(3)]
    addrs = [f"127.0.0.1:{p}" for p in rpc_ports]
    peers = ",".join(addrs)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env.get("PYTHONPATH", "")
    )

    procs = []
    try:
        for i in range(3):
            cmd = [
                sys.executable, "-m", "nomad_tpu.server.netagent",
                "--addr", addrs[i],
                "--peers", peers,
                "--http-port", str(http_ports[i]),
            ]
            if i > 0:
                cmd += ["--join", addrs[0]]
            procs.append(
                subprocess.Popen(
                    cmd,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    env=env,
                    cwd=repo_root,
                )
            )
        for p in procs:
            line = p.stdout.readline().decode()
            assert line.startswith("READY"), line

        leader_addr = _wait_http_leader(http_ports)
        leader_i = addrs.index(leader_addr)
        follower_is = [i for i in range(3) if i != leader_i]

        # HTTP write against a follower forwards to the leader ...
        job = job_to_dict(mock.job(id="proc-job"))
        out = _http_post(
            http_ports[follower_is[0]], "/v1/jobs", {"Job": job}
        )
        assert out["EvalID"]
        # ... and replicates to every server
        for port in http_ports:
            _wait_for(
                lambda p=port: any(
                    j["ID"] == "proc-job"
                    for j in _http_get(p, "/v1/jobs")
                ),
                "job visible on all servers",
            )

        # SIGKILL the leader; survivors elect a new one
        procs[leader_i].kill()
        survivor_ports = [http_ports[i] for i in follower_is]
        new_leader_addr = _wait_http_leader(
            survivor_ports, exclude=leader_addr, timeout=30
        )
        assert new_leader_addr != leader_addr

        # a follower write still works after failover
        new_leader_i = addrs.index(new_leader_addr)
        surviving_follower = [
            i for i in follower_is if i != new_leader_i
        ][0]
        job2 = job_to_dict(mock.job(id="proc-job-2"))
        out = _http_post(
            http_ports[surviving_follower], "/v1/jobs", {"Job": job2}
        )
        assert out["EvalID"]
        for i in follower_is:
            _wait_for(
                lambda p=http_ports[i]: any(
                    j["ID"] == "proc-job-2"
                    for j in _http_get(p, "/v1/jobs")
                ),
                "post-failover job visible on survivors",
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


def _wait_http_leader(http_ports, exclude=None, timeout=30):
    """Wait until every queried server agrees on one live leader."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        views = set()
        for port in http_ports:
            try:
                views.add(_http_get(port, "/v1/status/leader"))
            except Exception:  # noqa: BLE001 — server may be booting
                views.add(None)
        if (
            len(views) == 1
            and None not in views
            and (exclude is None or views != {exclude})
        ):
            (last,) = views
            if last:
                return last
        time.sleep(0.1)
    raise AssertionError(
        f"no agreed leader via HTTP (last views: {views})"
    )


def _wait_for(cond, what, timeout=15):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except Exception:  # noqa: BLE001
            pass
        time.sleep(0.1)
    raise AssertionError(f"timeout waiting for {what}")


# ---------------------------------------------------------------------------
# mutual TLS (reference helper/tlsutil/config.go: verify_incoming +
# verify_outgoing against a shared CA)
# ---------------------------------------------------------------------------


def _make_ca_and_certs(tmp_path, names=("server",), rogue=False):
    """Generate a CA and per-name cert/key pairs with the openssl CLI
    (the reference's test fixtures ship pre-generated material;
    generating keeps nothing secret-looking in the tree)."""
    import subprocess

    def run(*argv):
        subprocess.run(
            argv, check=True, capture_output=True, cwd=tmp_path
        )

    ca_key, ca_crt = tmp_path / "ca.key", tmp_path / "ca.crt"
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
        "-subj", "/CN=nomad-tpu-test-ca")
    out = {}
    for name in names:
        key = tmp_path / f"{name}.key"
        csr = tmp_path / f"{name}.csr"
        crt = tmp_path / f"{name}.crt"
        run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(csr),
            "-subj", f"/CN={name}")
        run("openssl", "x509", "-req", "-in", str(csr),
            "-CA", str(ca_crt), "-CAkey", str(ca_key),
            "-CAcreateserial", "-out", str(crt), "-days", "1")
        out[name] = (str(crt), str(key))
    if rogue:
        # self-signed cert NOT from the CA
        rkey, rcrt = tmp_path / "rogue.key", tmp_path / "rogue.crt"
        run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(rkey), "-out", str(rcrt), "-days", "1",
            "-subj", "/CN=rogue")
        out["rogue"] = (str(rcrt), str(rkey))
    return str(ca_crt), out


def test_tls_server_name_pins_role(tmp_path):
    """verify_server_hostname analog: with TLSConfig.server_name set,
    a CA-signed cert WITHOUT the server SAN is rejected on outgoing
    connections (cert-role confusion, ADVICE r3) while a proper
    server cert still works."""
    import subprocess

    from nomad_tpu.raft.tcp import TcpTransport, TLSConfig
    from nomad_tpu.raft.transport import TransportError

    def run(*argv):
        subprocess.run(argv, check=True, capture_output=True)

    ca_key, ca_crt = tmp_path / "ca.key", tmp_path / "ca.crt"
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
        "-subj", "/CN=nomad-ca")

    def issue(name, san=None):
        key = tmp_path / f"{name}.key"
        csr = tmp_path / f"{name}.csr"
        crt = tmp_path / f"{name}.crt"
        run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(csr),
            "-subj", f"/CN={name}")
        ext = tmp_path / f"{name}.ext"
        ext.write_text(
            f"subjectAltName=DNS:{san}\n" if san else
            "basicConstraints=CA:FALSE\n"
        )
        run("openssl", "x509", "-req", "-in", str(csr),
            "-CA", str(ca_crt), "-CAkey", str(ca_key),
            "-CAcreateserial", "-out", str(crt), "-days", "1",
            "-extfile", str(ext))
        return str(crt), str(key)

    server_crt = issue("server", san="server.global.nomad")
    client_crt = issue("client")  # CA-signed but no server SAN

    pin = "server.global.nomad"
    proper = TcpTransport(tls=TLSConfig(
        ca_file=str(ca_crt), cert_file=server_crt[0],
        key_file=server_crt[1], server_name=pin))
    addr = f"127.0.0.1:{free_port()}"
    proper.register(addr, lambda m, p: {"ok": True})

    imposter = TcpTransport(tls=TLSConfig(
        ca_file=str(ca_crt), cert_file=client_crt[0],
        key_file=client_crt[1], server_name=pin))
    imposter_addr = f"127.0.0.1:{free_port()}"
    imposter.register(imposter_addr, lambda m, p: {"ok": True})
    try:
        # server->server with the right SAN: fine
        caller = TcpTransport(tls=TLSConfig(
            ca_file=str(ca_crt), cert_file=server_crt[0],
            key_file=server_crt[1], server_name=pin))
        assert caller.rpc("a", addr, "ping", {})["ok"] is True
        # dialing a peer that presents the CLIENT cert: rejected
        with pytest.raises(TransportError):
            caller.rpc("a", imposter_addr, "ping", {})
        caller.close()
    finally:
        proper.close()
        imposter.close()


def test_tls_transport_roundtrip_and_rejection(tmp_path):
    from nomad_tpu.raft.tcp import TcpTransport, TLSConfig
    from nomad_tpu.raft.transport import TransportError

    ca, certs = _make_ca_and_certs(
        tmp_path, names=("server", "client"), rogue=True
    )
    srv_tls = TLSConfig(ca_file=ca, cert_file=certs["server"][0],
                        key_file=certs["server"][1])
    cli_tls = TLSConfig(ca_file=ca, cert_file=certs["client"][0],
                        key_file=certs["client"][1])

    server = TcpTransport(tls=srv_tls)
    addr = f"127.0.0.1:{free_port()}"
    server.register(addr, lambda method, payload: {
        "method": method, "echo": payload["x"]
    })
    try:
        # a CA-signed client talks fine
        good = TcpTransport(tls=cli_tls)
        resp = good.rpc("cli", addr, "ping", {"x": 41})
        assert resp == {"method": "ping", "echo": 41}
        good.close()

        # a plaintext client is rejected at the handshake
        plain = TcpTransport()
        with pytest.raises(TransportError):
            plain.rpc("cli", addr, "ping", {"x": 1})
        plain.close()

        # a rogue (non-CA) cert is rejected
        rogue_tls = TLSConfig(ca_file=ca,
                              cert_file=certs["rogue"][0],
                              key_file=certs["rogue"][1])
        rogue = TcpTransport(tls=rogue_tls)
        with pytest.raises(TransportError):
            rogue.rpc("cli", addr, "ping", {"x": 2})
        rogue.close()

        # and the good client still works afterwards (no poisoning)
        good2 = TcpTransport(tls=cli_tls)
        assert good2.rpc("cli", addr, "ping", {"x": 7})["echo"] == 7
        good2.close()
    finally:
        server.close()


def test_tls_cluster_elects_and_replicates(tmp_path):
    """A full 3-server cluster over mutual TLS: election, writes,
    replication — the transport swap is invisible to raft."""
    from nomad_tpu.raft.tcp import TcpTransport, TLSConfig
    from nomad_tpu.server.cluster import ClusterServer

    ca, certs = _make_ca_and_certs(
        tmp_path, names=("s0", "s1", "s2")
    )
    addrs = [f"127.0.0.1:{free_port()}" for _ in range(3)]
    transports = [
        TcpTransport(
            tls=TLSConfig(ca_file=ca, cert_file=certs[f"s{i}"][0],
                          key_file=certs[f"s{i}"][1])
        )
        for i in range(3)
    ]
    servers = [
        ClusterServer(addr, addrs, transports[i],
                      election_timeout=0.6, heartbeat_interval=0.15)
        for i, addr in enumerate(addrs)
    ]
    try:
        for s in servers:
            s.start()
        for s in servers[1:]:
            s.join(addrs[0])
        leader = _wait_leader(servers)
        leader.register_node(mock.node())
        job = mock.job(id="tls-job")
        leader.register_job(job)
        _wait_for(
            lambda: all(
                s.fsm.store.job_by_id("default", "tls-job") is not None
                for s in servers
            ),
            "replication over TLS",
        )
    finally:
        for s in servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001
                pass
        for t in transports:
            t.close()
