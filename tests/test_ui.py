"""Web UI detail pages (parity target: the information of the
reference's ui/app/routes/jobs/job and /clients/client routes,
rendered from the /v1 API by the built-in single-page app).

DOM-level: parse the served page's skeleton, assert the job/node
detail views render every section container, and contract-test the
exact endpoint payload shapes the page's JS consumes — a renamed API
key breaks these tests, not just the browser.
"""
import json
import re
import urllib.request
from html.parser import HTMLParser

import pytest

from nomad_tpu import mock
from nomad_tpu.api import start_http_server
from nomad_tpu.api.ui import UI_HTML
from nomad_tpu.server import Server
from nomad_tpu.structs import Task


@pytest.fixture(scope="module")
def ui_world():
    server = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=21)
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    node = mock.node()
    server.register_node(node)
    job = mock.job(id="uijob")
    job.task_groups[0].count = 2
    job.task_groups[0].tasks[0] = Task(
        name="web", driver="mock_driver", config={"run_for": -1}
    )
    from nomad_tpu.structs import UpdateStrategy

    job.task_groups[0].update = UpdateStrategy(max_parallel=1)
    server.register_job(job)
    assert server.drain_to_idle(10)
    yield {"server": server, "base": base, "node_id": node.id}
    http.stop()
    server.stop()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        body = resp.read()
        ctype = resp.headers.get("Content-Type", "")
    return body, ctype


class _IdCollector(HTMLParser):
    def __init__(self):
        super().__init__()
        self.ids = set()
        self.tags = set()

    def handle_starttag(self, tag, attrs):
        self.tags.add(tag)
        for k, v in attrs:
            if k == "id":
                self.ids.add(v)


def test_ui_serves_html_skeleton(ui_world):
    body, ctype = _get(ui_world["base"], "/ui")
    assert "text/html" in ctype
    dom = _IdCollector()
    dom.feed(body.decode())
    # top-level app containers
    assert {"view", "err", "live", "leader"} <= dom.ids
    assert {"nav", "script", "style"} <= dom.tags


def test_job_detail_view_renders_all_sections():
    """The jobView template must create every section container its
    renderers write into (facts grid, summary bars, task groups,
    allocs, deployments, evals)."""
    m = re.search(r"function jobView\(id\) \{(.+?)\n\}", UI_HTML, re.S)
    assert m, "jobView missing from UI"
    body = m.group(1)
    for section_id in ("facts", "sum", "tg", "a", "dep", "e"):
        assert f'id="{section_id}"' in body
    # live sections ride blocking queries, not one-shot fetches
    for live_path in ("/summary", "/allocations", "/deployments"):
        assert f"livePoll(`/v1/job/${{id}}{live_path}`" in body
    # structured rendering, not a JSON dump
    assert "JSON.stringify" not in body
    assert "summaryBar" in body and "kvGrid" in body


def test_node_detail_view_renders_all_sections():
    m = re.search(r"function nodeView\(id\) \{(.+?)\n\}", UI_HTML, re.S)
    assert m, "nodeView missing from UI"
    body = m.group(1)
    for section_id in ("facts", "res", "a", "ev", "dv", "at"):
        assert f'id="{section_id}"' in body
    assert "livePoll(`/v1/node/${id}/allocations`" in body
    assert "JSON.stringify" not in body
    assert "meter(" in body


def test_job_endpoints_match_ui_contract(ui_world):
    """Exact payload keys the jobView JS dereferences."""
    base = ui_world["base"]
    job = json.loads(_get(base, "/v1/job/uijob")[0])
    for key in ("id", "name", "type", "priority", "version",
                "namespace", "datacenters", "status", "task_groups"):
        assert key in job, key
    tg = job["task_groups"][0]
    assert {"name", "count", "tasks"} <= set(tg)
    assert {"name", "driver", "resources"} <= set(tg["tasks"][0])
    assert {"cpu", "memory_mb"} <= set(tg["tasks"][0]["resources"])

    s = json.loads(_get(base, "/v1/job/uijob/summary")[0])
    assert "Summary" in s
    counts = s["Summary"]["web"]
    assert {"Running", "Queued", "Complete", "Failed", "Starting",
            "Lost"} <= set(counts)
    # no client attached: placed allocs count as Starting
    assert counts["Running"] + counts["Starting"] == 2

    allocs = json.loads(_get(base, "/v1/job/uijob/allocations")[0])
    a = allocs[0]
    for key in ("id", "job_id", "task_group", "node_id",
                "desired_status", "client_status",
                "allocated_resources"):
        assert key in a, key
    tasks = a["allocated_resources"]["tasks"]
    assert all(
        {"cpu", "memory_mb"} <= set(t) for t in tasks.values()
    )

    ds = json.loads(_get(base, "/v1/job/uijob/deployments")[0])
    assert ds, "update-strategy job must produce a deployment"
    d = ds[0]
    assert {"id", "job_version", "status", "task_groups"} <= set(d)
    st = d["task_groups"]["web"]
    assert {"desired_total", "placed_allocs", "healthy_allocs",
            "unhealthy_allocs", "desired_canaries",
            "placed_canaries", "promoted"} <= set(st)


def test_node_endpoints_match_ui_contract(ui_world):
    base, node_id = ui_world["base"], ui_world["node_id"]
    n = json.loads(_get(base, f"/v1/node/{node_id}")[0])
    for key in ("id", "name", "datacenter", "status",
                "scheduling_eligibility", "drain", "attributes",
                "node_resources", "events"):
        assert key in n, key
    assert {"cpu", "memory_mb", "disk_mb"} <= set(n["node_resources"])
    # registration event is recorded with the fields the UI renders
    ev = n["events"][0]
    assert {"message", "subsystem", "timestamp"} <= set(ev)


def test_alloc_detail_view_renders_all_sections():
    m = re.search(
        r"function allocView\(id\) \{(.+?)\n\}", UI_HTML, re.S
    )
    assert m, "allocView missing from UI"
    body = m.group(1)
    for section_id in ("facts", "tasks", "res", "logs"):
        assert f'id="{section_id}"' in body
    assert "livePoll(`/v1/allocation/${id}`" in body
    assert "JSON.stringify" not in body
    # the live log tail rides the chunked follow endpoint
    assert "tailLogs" in body
    assert "/v1/client/fs/logs/" in UI_HTML


def test_alloc_endpoint_matches_ui_contract(ui_world):
    base = ui_world["base"]
    allocs = json.loads(_get(base, "/v1/job/uijob/allocations")[0])
    a = json.loads(
        _get(base, f"/v1/allocation/{allocs[0]['id']}")[0]
    )
    for key in ("id", "name", "job_id", "node_id", "task_group",
                "desired_status", "client_status", "task_states",
                "create_time", "allocated_resources"):
        assert key in a, key
