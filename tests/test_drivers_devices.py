"""Driver plugins (java/qemu/docker), artifact getter, device plugin
framework (reference drivers/java, drivers/qemu, drivers/docker,
taskrunner/getter, plugins/device + client/devicemanager).
"""
import hashlib
import os
import shutil
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client.devices import (
    DeviceManager,
    DevicePlugin,
    ReservationSpec,
)
from nomad_tpu.client.drivers import (
    BUILTIN_DRIVERS,
    DockerDriver,
    JavaDriver,
    QemuDriver,
    new_driver,
)
from nomad_tpu.client.drivers.base import TaskConfig
from nomad_tpu.client.getter import ArtifactError, fetch_all, fetch_artifact
from nomad_tpu.structs import Node, NodeDeviceResource


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def test_all_reference_drivers_registered():
    for name in ("mock_driver", "exec", "raw_exec", "java", "qemu",
                 "docker"):
        assert name in BUILTIN_DRIVERS
        assert new_driver(name) is not None


def test_java_driver_fingerprint_gates_on_jvm():
    d = JavaDriver()
    fp = d.fingerprint()
    if shutil.which("java"):
        assert fp["driver.java"] == "1"
    else:
        assert fp["driver.java"] == "0"
        with pytest.raises(RuntimeError):
            d._build_command(
                TaskConfig(config={"jar_path": "/x.jar"})
            )


def test_java_driver_command_shapes():
    d = JavaDriver()
    d._java = "/usr/bin/java"  # force-detect for argv assembly
    argv = d._build_command(
        TaskConfig(
            config={
                "jar_path": "app.jar",
                "jvm_options": ["-Xmx64m"],
                "args": ["serve"],
            }
        )
    )
    assert argv == ["/usr/bin/java", "-Xmx64m", "-jar", "app.jar",
                    "serve"]
    argv = d._build_command(
        TaskConfig(
            config={"class": "Main", "class_path": "lib/*"}
        )
    )
    assert argv == ["/usr/bin/java", "-cp", "lib/*", "Main"]
    with pytest.raises(ValueError):
        d._build_command(TaskConfig(config={}))


def test_qemu_driver_command_shapes(tmp_path):
    d = QemuDriver()
    d._qemu = "/usr/bin/qemu-system-x86_64"
    cfg = TaskConfig(
        config={
            "image_path": "vm.qcow2",
            "port_map": {"22": 2222},
        },
        task_dir=str(tmp_path),
    )
    cfg.resources = mock.job().task_groups[0].tasks[0].resources
    argv = d._build_command(cfg)
    assert argv[0] == "/usr/bin/qemu-system-x86_64"
    assert f"file={tmp_path}/vm.qcow2,format=qcow2" in argv
    assert any("hostfwd=tcp::2222-:22" in a for a in argv)
    with pytest.raises(ValueError):
        d._build_command(TaskConfig(config={}))


def test_docker_driver_gates_on_daemon():
    d = DockerDriver()
    fp = d.fingerprint()
    if not d._daemon_reachable():
        assert fp["driver.docker"] == "0"
        with pytest.raises(RuntimeError):
            d.start_task(TaskConfig(id="t", config={"image": "alpine"}))


def test_docker_container_spec():
    d = DockerDriver(sock_path="/nonexistent.sock")
    cfg = TaskConfig(
        id="t1",
        alloc_id="a1",
        env={"FOO": "bar"},
        alloc_dir="/data/a1",
        config={
            "image": "redis:6",
            "command": "redis-server",
            "args": ["--port", "6380"],
            "port_map": {"6380": 16380},
        },
    )
    spec = d._container_spec(cfg)
    assert spec["Image"] == "redis:6"
    assert "FOO=bar" in spec["Env"]
    assert "/data/a1:/alloc" in spec["HostConfig"]["Binds"]
    assert spec["HostConfig"]["PortBindings"]["6380/tcp"] == [
        {"HostPort": "16380"}
    ]
    assert spec["Cmd"] == ["redis-server", "--port", "6380"]
    assert spec["Labels"]["nomad.alloc_id"] == "a1"
    with pytest.raises(ValueError):
        d._container_spec(TaskConfig(config={}))


# ---------------------------------------------------------------------------
# artifact getter
# ---------------------------------------------------------------------------


def test_fetch_local_file_with_checksum(tmp_path):
    src = tmp_path / "artifact.bin"
    src.write_bytes(b"payload-data")
    digest = hashlib.sha256(b"payload-data").hexdigest()
    dest = tmp_path / "local"
    out = fetch_artifact(
        {
            "source": str(src),
            "options": {"checksum": f"sha256:{digest}"},
        },
        str(dest),
    )
    assert os.path.exists(out)

    with pytest.raises(ArtifactError):
        fetch_artifact(
            {
                "source": str(src),
                "options": {"checksum": "sha256:" + "0" * 64},
            },
            str(dest),
        )


def test_fetch_directory_and_missing(tmp_path):
    srcdir = tmp_path / "bundle"
    srcdir.mkdir()
    (srcdir / "a.txt").write_text("a")
    dest = tmp_path / "local"
    out = fetch_all([{"source": str(srcdir)}], str(dest))
    assert os.path.exists(os.path.join(out[0], "a.txt"))
    with pytest.raises(ArtifactError):
        fetch_artifact({"source": str(tmp_path / "nope")}, str(dest))


def test_fetch_rejects_escaping_destination(tmp_path):
    src = tmp_path / "x"
    src.write_text("x")
    with pytest.raises(ArtifactError):
        fetch_artifact(
            {"source": str(src), "destination": "../../etc"},
            str(tmp_path / "local"),
        )


# ---------------------------------------------------------------------------
# device plugin framework
# ---------------------------------------------------------------------------


class FakeGPUPlugin(DevicePlugin):
    vendor = "acme"
    type = "gpu"

    def fingerprint(self):
        return [
            NodeDeviceResource(
                vendor="acme", type="gpu", name="a100",
                instance_ids=["g0", "g1"],
                attributes={"memory_mb": 40960},
            )
        ]

    def reserve(self, device_ids):
        return ReservationSpec(
            envs={"ACME_VISIBLE_DEVICES": ",".join(device_ids)}
        )

    def stats(self):
        return {"g0": {"util": 0.5}, "g1": {"util": 0.0}}


def test_device_manager_fingerprint_and_reserve():
    node = Node()
    dm = DeviceManager(plugins=[FakeGPUPlugin()])
    dm.fingerprint_node(node)
    devs = node.node_resources.devices
    assert len(devs) == 1 and devs[0].name == "a100"
    assert devs[0].instance_ids == ["g0", "g1"]

    spec = dm.reserve("alloc1", "acme", "gpu", "a100", ["g1"])
    assert spec.envs["ACME_VISIBLE_DEVICES"] == "g1"
    assert dm.reserved_ids("alloc1") == ["g1"]
    dm.free("alloc1")
    assert dm.reserved_ids("alloc1") == []

    with pytest.raises(KeyError):
        dm.reserve("a2", "nvidia", "gpu", "v100", ["x"])

    stats = dm.all_stats()
    assert stats["acme/gpu"]["g0"]["util"] == 0.5


def test_device_manager_refingerprint_updates_in_place():
    node = Node()
    dm = DeviceManager(plugins=[FakeGPUPlugin()])
    dm.fingerprint_node(node)
    dm.fingerprint_node(node)
    assert len(node.node_resources.devices) == 1


# ---------------------------------------------------------------------------
# dispatch payload end-to-end
# ---------------------------------------------------------------------------


def wait_until(cond, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def test_dispatch_payload_written_to_task_dir(tmp_path):
    from nomad_tpu.client import Client
    from nomad_tpu.server import Server
    from nomad_tpu.structs import Task

    srv = Server()
    srv.start()
    cli = Client(
        srv, node=Node(), data_dir=str(tmp_path),
        heartbeat_interval=5.0,
    )
    cli.start()
    try:
        job = mock.job(id="etl")
        job.type = "batch"
        job.parameterized = {"payload": "required"}
        tg = job.task_groups[0]
        tg.count = 1
        tg.tasks[0] = Task(
            name="consume",
            driver="raw_exec",
            dispatch_payload_file="input.json",
            config={
                "command": "/bin/sh",
                "args": ["-c", "cat input.json"],
            },
        )
        srv.register_job(job)
        child = srv.dispatch_job(
            "default", "etl", payload=b'{"rows": 3}'
        )
        assert child.payload == b'{"rows": 3}'
        assert wait_until(
            lambda: any(
                a.client_status == "complete"
                for a in srv.store.allocs_by_job("default", child.id)
            )
        ), "dispatched alloc did not complete"
        alloc = srv.store.allocs_by_job("default", child.id)[0]
        out = srv.read_task_log(alloc.id, "consume", "stdout")
        assert b'{"rows": 3}' in out
    finally:
        cli.stop()
        srv.stop()
