"""Differential parity: the vectorized TPU stack must produce placements
bit-identical to the oracle iterator chain (the reference semantics),
across the BASELINE.json config families (SURVEY.md section 7.1 step 3).
"""
import random

import pytest

from nomad_tpu import mock
from nomad_tpu.sched.generic_sched import BatchScheduler, ServiceScheduler
from nomad_tpu.sched.testing import Harness
from nomad_tpu.structs import (
    Affinity,
    Constraint,
    PreemptionConfig,
    SchedulerConfiguration,
    Spread,
    SpreadTarget,
    compute_node_class,
)

from conftest import heterogeneous_cluster


def run_both(harness, factory, evaluation, seed):
    """Run oracle then TPU scheduler against identical (unmutated) state;
    returns both placement lists."""
    harness.reject_plan = True
    harness.process(factory, evaluation, use_tpu=False, seed=seed)
    oracle = sorted(
        (a.name, a.node_id)
        for v in harness.plans[-1].node_allocation.values()
        for a in v
    )
    oracle_stops = sorted(
        (a.id, a.desired_status)
        for v in harness.plans[-1].node_update.values()
        for a in v
    )
    harness.process(factory, evaluation, use_tpu=True, seed=seed)
    tpu = sorted(
        (a.name, a.node_id)
        for v in harness.plans[-1].node_allocation.values()
        for a in v
    )
    tpu_stops = sorted(
        (a.id, a.desired_status)
        for v in harness.plans[-1].node_update.values()
        for a in v
    )
    return (oracle, oracle_stops), (tpu, tpu_stops)


def assert_identical(harness, factory, evaluation, seed):
    (o, os_), (t, ts) = run_both(harness, factory, evaluation, seed)
    assert o == t, f"placements diverged:\n oracle={o}\n tpu={t}"
    assert os_ == ts, "stop sets diverged"
    return o


@pytest.mark.parametrize("trial", range(6))
def test_service_binpack_parity(harness, trial):
    """BASELINE config 1: plain service binpack."""
    heterogeneous_cluster(harness, 60, seed=trial)
    job = mock.job(datacenters=["dc1", "dc2"])
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    placements = assert_identical(
        harness, ServiceScheduler, ev, seed=trial * 17 + 3
    )
    assert len(placements) == 10


@pytest.mark.parametrize("trial", range(4))
def test_batch_parity(harness, trial):
    """BASELINE config 2: batch jobs, power-of-two-choices limit 2."""
    heterogeneous_cluster(harness, 40, seed=trial + 100)
    job = mock.batch_job(datacenters=["dc1", "dc2"])
    job.task_groups[0].count = 7
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id, type="batch")
    placements = assert_identical(
        harness, BatchScheduler, ev, seed=trial * 13 + 5
    )
    assert len(placements) == 7


@pytest.mark.parametrize("trial", range(4))
def test_constraints_parity(harness, trial):
    """Constraint operators incl. regex and version (the reference's
    'escaped' cases) via LUT compilation."""
    heterogeneous_cluster(harness, 50, seed=trial + 200)
    job = mock.job(datacenters=["dc1", "dc2"])
    job.constraints = [
        Constraint("${attr.kernel.name}", "linux", "="),
        Constraint("${attr.os.version}", "2[02].04", "regexp"),
    ]
    job.task_groups[0].constraints = [
        Constraint("${attr.nomad.version}", ">= 0.9", "version"),
        Constraint("${attr.rack}", "r4", "!="),
    ]
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    assert_identical(harness, ServiceScheduler, ev, seed=trial * 7 + 1)


@pytest.mark.parametrize("trial", range(4))
def test_spread_affinity_parity(harness, trial):
    """BASELINE config 3: spread + node affinity across DCs."""
    heterogeneous_cluster(
        harness, 60, seed=trial + 300, datacenters=("dc1", "dc2", "dc3")
    )
    job = mock.job(datacenters=["dc1", "dc2", "dc3"])
    job.affinities = [
        Affinity("${attr.rack}", "r1", "=", 50),
        Affinity("${node.datacenter}", "dc3", "=", -30),
    ]
    job.spreads = [
        Spread(
            attribute="${node.datacenter}",
            weight=70,
            targets=(
                SpreadTarget("dc1", 50),
                SpreadTarget("dc2", 30),
                SpreadTarget("dc3", 20),
            ),
        )
    ]
    job.task_groups[0].count = 12
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    placements = assert_identical(
        harness, ServiceScheduler, ev, seed=trial * 11 + 9
    )
    assert len(placements) == 12


@pytest.mark.parametrize("trial", range(3))
def test_even_spread_parity(harness, trial):
    """Spread with no targets: even-spread scoring."""
    heterogeneous_cluster(
        harness, 45, seed=trial + 400, datacenters=("dc1", "dc2", "dc3")
    )
    job = mock.job(datacenters=["dc1", "dc2", "dc3"])
    job.spreads = [Spread(attribute="${node.datacenter}", weight=50)]
    job.task_groups[0].count = 9
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    assert_identical(harness, ServiceScheduler, ev, seed=trial + 21)


@pytest.mark.parametrize("trial", range(3))
def test_distinct_hosts_parity(harness, trial):
    heterogeneous_cluster(harness, 30, seed=trial + 500)
    job = mock.job(datacenters=["dc1", "dc2"])
    job.constraints.append(Constraint(operand="distinct_hosts"))
    job.task_groups[0].count = 8
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    placements = assert_identical(
        harness, ServiceScheduler, ev, seed=trial + 31
    )
    nodes_used = {n for _, n in placements}
    assert len(nodes_used) == 8


@pytest.mark.parametrize("trial", range(3))
def test_distinct_property_parity(harness, trial):
    heterogeneous_cluster(harness, 40, seed=trial + 600, racks=6)
    job = mock.job(datacenters=["dc1", "dc2"])
    job.constraints.append(
        Constraint("${attr.rack}", "2", "distinct_property")
    )
    job.task_groups[0].count = 6
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    assert_identical(harness, ServiceScheduler, ev, seed=trial + 41)


def test_existing_allocs_and_scale_up_parity(harness):
    """Second eval on a half-placed job: anti-affinity collisions and
    proposed-usage deltas must match."""
    nodes = heterogeneous_cluster(harness, 40, seed=700)
    job = mock.job(datacenters=["dc1", "dc2"])
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    # apply the first eval for real
    harness.process(ServiceScheduler, ev, use_tpu=False, seed=1)
    # scale up
    import dataclasses

    job2 = mock.job(datacenters=["dc1", "dc2"])
    job2.id = job.id
    job2.task_groups[0].count = 18
    harness.store.upsert_job(job2)
    ev2 = mock.evaluation(job_id=job.id)
    assert_identical(harness, ServiceScheduler, ev2, seed=2)


def test_exhaustion_creates_blocked_eval_parity(harness):
    """More asks than capacity: both paths must fail the same placements
    and spawn a blocked eval."""
    for _ in range(3):
        n = mock.node()
        n.node_resources.cpu = 1000
        n.node_resources.memory_mb = 1024
        n.computed_class = compute_node_class(n)
        harness.store.upsert_node(n)
    job = mock.job()
    job.task_groups[0].count = 20
    job.task_groups[0].tasks[0].resources.cpu = 400
    job.task_groups[0].tasks[0].resources.memory_mb = 300
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)

    harness.reject_plan = True
    harness.process(ServiceScheduler, ev, use_tpu=False, seed=3)
    oracle_blocked = len(harness.create_evals)
    oracle_placed = sum(
        len(v) for v in harness.plans[-1].node_allocation.values()
    )
    harness.create_evals.clear()
    harness.process(ServiceScheduler, ev, use_tpu=True, seed=3)
    tpu_blocked = len(harness.create_evals)
    tpu_placed = sum(
        len(v) for v in harness.plans[-1].node_allocation.values()
    )
    assert oracle_placed == tpu_placed
    # one blocked eval from the failed-placement pass; with the plan
    # rejected every attempt, the retry-exhaustion path adds a second
    # (max-plan-attempts) blocked eval, as the reference does
    # (generic_sched.go:162,265)
    assert oracle_blocked == tpu_blocked
    assert oracle_blocked >= 1


def test_spread_algorithm_parity(harness):
    """Scheduler algorithm 'spread' (worst-fit) instead of binpack."""
    heterogeneous_cluster(harness, 30, seed=800)
    harness.store.set_scheduler_config(
        SchedulerConfiguration(scheduler_algorithm="spread")
    )
    job = mock.job(datacenters=["dc1", "dc2"])
    harness.store.upsert_job(job)
    ev = mock.evaluation(job_id=job.id)
    assert_identical(harness, ServiceScheduler, ev, seed=4)


def test_preemption_parity(harness):
    """Preemption retry path: TPU delegates to the shadow oracle chain
    with the identical visit order."""
    # small cluster, filled with low-priority allocs
    for _ in range(4):
        n = mock.node()
        n.node_resources.cpu = 2000
        n.node_resources.memory_mb = 2048
        n.computed_class = compute_node_class(n)
        harness.store.upsert_node(n)
    low = mock.job()
    low.priority = 20
    low.task_groups[0].count = 4
    low.task_groups[0].tasks[0].resources.cpu = 1500
    low.task_groups[0].tasks[0].resources.memory_mb = 1200
    harness.store.upsert_job(low)
    ev0 = mock.evaluation(job_id=low.id)
    harness.process(ServiceScheduler, ev0, use_tpu=False, seed=5)

    harness.store.set_scheduler_config(
        SchedulerConfiguration(
            preemption_config=PreemptionConfig(
                service_scheduler_enabled=True
            )
        )
    )
    high = mock.job()
    high.priority = 80
    high.task_groups[0].count = 2
    high.task_groups[0].tasks[0].resources.cpu = 1200
    high.task_groups[0].tasks[0].resources.memory_mb = 1000
    harness.store.upsert_job(high)
    ev = mock.evaluation(job_id=high.id, priority=80)
    (o, _), (t, _) = run_both(harness, ServiceScheduler, ev, seed=6)
    assert o == t
    assert len(o) == 2
    # preemptions must also match
    harness.reject_plan = True
    harness.process(ServiceScheduler, ev, use_tpu=False, seed=7)
    o_pre = sorted(
        a.id
        for v in harness.plans[-1].node_preemptions.values()
        for a in v
    )
    harness.process(ServiceScheduler, ev, use_tpu=True, seed=7)
    t_pre = sorted(
        a.id
        for v in harness.plans[-1].node_preemptions.values()
        for a in v
    )
    assert o_pre == t_pre
    assert o_pre  # something actually got preempted


def test_preemption_parity_mixed_fleet(harness):
    """Vectorized preemption select (SURVEY 7.1 step 5): a fleet mixing
    free nodes, preemptible nodes (several priority tiers), and
    hopeless nodes (high-priority occupants the shortfall filter must
    skip) — winners AND preemption sets must match the oracle chain
    bit for bit."""
    import random as _random

    rng = _random.Random(3)
    nodes = []
    for i in range(12):
        n = mock.node()
        n.node_resources.cpu = 2000
        n.node_resources.memory_mb = 2048
        n.computed_class = compute_node_class(n)
        nodes.append(n)
        harness.store.upsert_node(n)

    # fill 9 of 12 nodes with occupants at different priorities:
    # pri 20 (preemptible), pri 75 (not preemptible vs pri-80 job)
    for tier, (pri, count) in enumerate(((20, 5), (75, 4))):
        occ = mock.job(id=f"occ-{tier}")
        occ.priority = pri
        occ.task_groups[0].count = count
        occ.task_groups[0].tasks[0].resources.cpu = 1500
        occ.task_groups[0].tasks[0].resources.memory_mb = 1600
        harness.store.upsert_job(occ)
        ev0 = mock.evaluation(job_id=occ.id, priority=pri)
        harness.process(ServiceScheduler, ev0, use_tpu=False, seed=tier)

    harness.store.set_scheduler_config(
        SchedulerConfiguration(
            preemption_config=PreemptionConfig(
                service_scheduler_enabled=True
            )
        )
    )
    high = mock.job(id="high")
    high.priority = 80
    high.task_groups[0].count = 6
    high.task_groups[0].tasks[0].resources.cpu = 1200
    high.task_groups[0].tasks[0].resources.memory_mb = 1000
    harness.store.upsert_job(high)
    ev = mock.evaluation(job_id=high.id, priority=80)

    harness.reject_plan = True
    harness.process(ServiceScheduler, ev, use_tpu=False, seed=9)
    oracle_plan = harness.plans[-1]
    o_place = sorted(
        (a.name, a.node_id)
        for v in oracle_plan.node_allocation.values()
        for a in v
    )
    o_pre = sorted(
        a.id
        for v in oracle_plan.node_preemptions.values()
        for a in v
    )
    harness.process(ServiceScheduler, ev, use_tpu=True, seed=9)
    tpu_plan = harness.plans[-1]
    t_place = sorted(
        (a.name, a.node_id)
        for v in tpu_plan.node_allocation.values()
        for a in v
    )
    t_pre = sorted(
        a.id
        for v in tpu_plan.node_preemptions.values()
        for a in v
    )
    assert o_place == t_place
    assert o_pre == t_pre
    assert o_pre, "scenario must actually exercise preemption"
