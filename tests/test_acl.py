"""ACL system tests (reference model: acl/acl_test.go,
nomad/acl_endpoint_test.go).
"""
import json
import urllib.error
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.acl import ACLStore, Policy, Token
from nomad_tpu.api import start_http_server
from nomad_tpu.server import Server


def test_management_token_allows_everything():
    store = ACLStore(enabled=True)
    token = store.bootstrap()
    assert store.allowed(token.secret_id, "default", "submit-job")
    assert store.allowed(token.secret_id, "any-ns", "node:write")


def test_policy_capabilities():
    store = ACLStore(enabled=True)
    store.upsert_policy(
        Policy.from_dict(
            "readonly",
            {"namespaces": {"default": {"policy": "read"}},
             "node": "read"},
        )
    )
    token = store.create_token(Token(policies=["readonly"]))
    sid = token.secret_id
    assert store.allowed(sid, "default", "read-job")
    assert not store.allowed(sid, "default", "submit-job")
    assert store.allowed(sid, "default", "node:read")
    assert not store.allowed(sid, "default", "node:write")
    # other namespaces: nothing granted
    assert not store.allowed(sid, "other", "read-job")


def test_policy_glob_namespaces():
    store = ACLStore(enabled=True)
    store.upsert_policy(
        Policy.from_dict(
            "web",
            {
                "namespaces": {
                    "web-*": {"capabilities": ["submit-job", "read-job"]}
                }
            },
        )
    )
    token = store.create_token(Token(policies=["web"]))
    assert store.allowed(token.secret_id, "web-frontend", "submit-job")
    assert not store.allowed(token.secret_id, "api", "submit-job")


def test_deny_policy_wins():
    store = ACLStore(enabled=True)
    store.upsert_policy(
        Policy.from_dict(
            "deny-default",
            {"namespaces": {"default": {"policy": "deny"}}},
        )
    )
    token = store.create_token(Token(policies=["deny-default"]))
    assert not store.allowed(token.secret_id, "default", "read-job")


def test_unknown_token_denied():
    store = ACLStore(enabled=True)
    assert not store.allowed("bogus-secret", "default", "read-job")


def test_anonymous_denied_by_default():
    store = ACLStore(enabled=True)
    assert not store.allowed("", "default", "submit-job")


@pytest.fixture
def acl_api():
    server = Server(num_schedulers=1, seed=44, acl_enabled=True)
    server.start()
    http = start_http_server(server, port=0)
    base = f"http://127.0.0.1:{http.port}"
    yield server, base
    http.stop()
    server.stop()


def _req(base, method, path, body=None, token=None):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode() if body is not None else None,
        method=method,
        headers={"Content-Type": "application/json"},
    )
    if token:
        req.add_header("X-Nomad-Token", token)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read() or b"null")


def test_http_acl_enforcement(acl_api):
    server, base = acl_api
    # anonymous job submission is denied
    from nomad_tpu.api.codec import job_to_dict

    job_payload = {"Job": job_to_dict(mock.job(id="acl-test"))}
    with pytest.raises(urllib.error.HTTPError) as exc:
        _req(base, "POST", "/v1/jobs", job_payload)
    assert exc.value.code == 403

    # bootstrap a management token
    boot = _req(base, "POST", "/v1/acl/bootstrap")
    mgmt = boot["SecretID"]

    # management token may submit
    resp = _req(base, "POST", "/v1/jobs", job_payload, token=mgmt)
    assert resp["EvalID"]

    # create a read-only policy + client token
    _req(
        base, "POST", "/v1/acl/policy/readonly",
        {"Rules": {"namespaces": {"default": {"policy": "read"}}}},
        token=mgmt,
    )
    tok = _req(
        base, "POST", "/v1/acl/tokens",
        {"Name": "reader", "Policies": ["readonly"]},
        token=mgmt,
    )
    reader = tok["SecretID"]

    # reader can list jobs but not submit
    jobs = _req(base, "GET", "/v1/jobs", token=reader)
    assert any(j["ID"] == "acl-test" for j in jobs)
    with pytest.raises(urllib.error.HTTPError) as exc:
        _req(base, "POST", "/v1/jobs", job_payload, token=reader)
    assert exc.value.code == 403


def test_namespace_list_filtered_by_token_scope(acl_api):
    """GET /v1/namespaces returns only namespaces the token has a
    capability for (reference namespace_endpoint.go ListNamespaces):
    a token scoped to one namespace must not learn the others'
    names/descriptions (ADVICE r3)."""
    server, base = acl_api
    boot = _req(base, "POST", "/v1/acl/bootstrap")
    mgmt = boot["SecretID"]
    for name in ("team-a", "team-b"):
        _req(
            base, "POST", "/v1/namespaces",
            {"Name": name, "Description": f"{name} workloads"},
            token=mgmt,
        )
    # management sees everything
    names = {
        n["Name"]
        for n in _req(base, "GET", "/v1/namespaces", token=mgmt)
    }
    assert {"default", "team-a", "team-b"} <= names

    # a token scoped to team-a sees ONLY team-a
    _req(
        base, "POST", "/v1/acl/policy/team-a-read",
        {"Rules": {"namespaces": {"team-a": {"policy": "read"}}}},
        token=mgmt,
    )
    tok = _req(
        base, "POST", "/v1/acl/tokens",
        {"Name": "scoped", "Policies": ["team-a-read"]},
        token=mgmt,
    )
    scoped = {
        n["Name"]
        for n in _req(
            base, "GET", "/v1/namespaces", token=tok["SecretID"]
        )
    }
    assert scoped == {"team-a"}

    # an anonymous/unknown token gets a 403, not the full list
    with pytest.raises(urllib.error.HTTPError) as exc:
        _req(base, "GET", "/v1/namespaces")
    assert exc.value.code == 403

    # a VALID token whose policies grant no namespace capability gets
    # an empty list, not 403 (reference ListNamespaces filters; only
    # anonymous/invalid tokens are denied) — ADVICE r4
    _req(
        base, "POST", "/v1/acl/policy/node-only",
        {"Rules": {"node": "read"}},
        token=mgmt,
    )
    tok2 = _req(
        base, "POST", "/v1/acl/tokens",
        {"Name": "nodescope", "Policies": ["node-only"]},
        token=mgmt,
    )
    assert (
        _req(base, "GET", "/v1/namespaces", token=tok2["SecretID"])
        == []
    )
