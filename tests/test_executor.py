"""Executor isolation layer tests (reference model:
drivers/shared/executor/executor_test.go + executor_linux_test.go —
launch/wait/stop via a separate executor process, chroot + cgroup
isolation, reattach across driver restarts).
"""
import os
import sys
import time

import pytest

from nomad_tpu.client.drivers.base import TaskConfig
from nomad_tpu.client.executor import (
    CGROUP_ROOT,
    CgroupSlice,
    ExecutorClient,
    build_chroot,
    link_command_env,
)
from nomad_tpu.structs import Resources

pytestmark = pytest.mark.skipif(
    sys.platform != "linux", reason="executor isolation is linux-only"
)

IS_ROOT = os.geteuid() == 0


def _cgroups_writable() -> bool:
    probe = os.path.join(
        CGROUP_ROOT,
        "cgroup.controllers" in os.listdir(CGROUP_ROOT)
        and "nomad_probe"
        or "memory/nomad_probe",
    )
    try:
        os.makedirs(probe, exist_ok=True)
        os.rmdir(probe)
        return True
    except OSError:
        return False


@pytest.fixture
def client():
    c = ExecutorClient.spawn()
    yield c
    c.shutdown()


def test_executor_launch_wait_exit(client, tmp_path):
    out = str(tmp_path / "out.txt")
    info = client.launch(
        {
            "task_id": "t1",
            "argv": ["/bin/sh", "-c", "echo from-executor; exit 3"],
            "stdout_path": out,
            "env": {"PATH": "/bin:/usr/bin"},
        }
    )
    assert info["pid"] > 0
    res = client.wait("t1", timeout=10)
    assert res["exit_code"] == 3
    with open(out) as f:
        assert "from-executor" in f.read()
    client.destroy("t1")
    assert client.list_tasks() == []


def test_executor_stop_signals_process_group(client):
    client.launch(
        {
            "task_id": "t2",
            # the child spawns its own child; stop must kill both
            "argv": ["/bin/sh", "-c", "sleep 30 & wait"],
        }
    )
    t0 = time.monotonic()
    client.stop("t2", timeout=2.0)
    res = client.wait("t2", timeout=5)
    assert res is not None
    assert res["signal"] == 15
    assert time.monotonic() - t0 < 5.0
    client.destroy("t2")


def test_executor_stats(client):
    client.launch({"task_id": "t3", "argv": ["/bin/sleep", "3"]})
    time.sleep(0.2)
    stats = client.stats("t3")
    assert stats.get("memory_rss_bytes", 0) > 0
    client.stop("t3", timeout=1.0)
    client.destroy("t3")


@pytest.mark.skipif(
    not (IS_ROOT and _cgroups_writable()),
    reason="needs root + writable cgroupfs",
)
def test_executor_cgroup_limits(client):
    info = client.launch(
        {
            "task_id": "t4",
            "argv": ["/bin/sleep", "2"],
            "memory_mb": 64,
            "cpu_shares": 256,
        }
    )
    assert info["isolation"]["cgroups"]
    stats = client.stats("t4")
    assert stats.get("memory_rss_bytes", 0) > 0
    client.stop("t4", timeout=1.0)
    client.destroy("t4")
    # the cgroup directory is removed on destroy
    slice_ = CgroupSlice("t4")
    leftovers = [
        p
        for p in (
            os.path.join(CGROUP_ROOT, "nomad_tpu", "t4"),
            os.path.join(CGROUP_ROOT, "memory", "nomad_tpu", "t4"),
            os.path.join(CGROUP_ROOT, "cpu", "nomad_tpu", "t4"),
        )
        if os.path.exists(p)
    ]
    assert leftovers == [], leftovers


@pytest.mark.skipif(not IS_ROOT, reason="chroot needs root")
def test_executor_chroot_hides_host_fs(client, tmp_path):
    marker = tmp_path / "marker-outside"
    marker.write_text("x")
    croot = str(tmp_path / "sandbox")
    out = str(tmp_path / "out.txt")
    info = client.launch(
        {
            "task_id": "t5",
            "argv": [
                "/bin/sh",
                "-c",
                f"test -e {marker} && echo VISIBLE || echo HIDDEN",
            ],
            "chroot": croot,
            "chroot_populate": "auto",
            "stdout_path": out,
        }
    )
    assert info["isolation"]["chroot"]
    res = client.wait("t5", timeout=10)
    assert res["exit_code"] == 0
    with open(out) as f:
        assert "HIDDEN" in f.read()
    client.destroy("t5")


@pytest.mark.skipif(not IS_ROOT, reason="bind sandbox needs root")
def test_executor_bind_sandbox_full_system_readonly(client, tmp_path):
    """The default sandbox bind-mounts the system dirs read-only in a
    private mount namespace: arbitrary binaries run, host files stay
    hidden, writes to system paths fail, and nothing leaks host-side."""
    marker = tmp_path / "marker"
    marker.write_text("x")
    croot = str(tmp_path / "sandbox")
    out = str(tmp_path / "out.txt")
    info = client.launch(
        {
            "task_id": "tb",
            "argv": [
                "/bin/sh",
                "-c",
                # /bin/ls is a real binary (not a builtin): proves the
                # full system tree is visible inside the sandbox; the
                # >/dev/null redirect also needs a real device node
                f"ls /usr/bin >/dev/null && echo BINDOK;"
                f" test -c /dev/null && echo DEVOK;"
                f" test -e {marker} && echo VISIBLE || echo HIDDEN;"
                f" touch /usr/bin/nope 2>/dev/null && echo RW || echo RO",
            ],
            "chroot": croot,
            "chroot_populate": "bind",
            "stdout_path": out,
        }
    )
    assert info["isolation"]["chroot"]
    res = client.wait("tb", timeout=10)
    assert res["exit_code"] == 0
    got = open(out).read()
    assert "BINDOK" in got and "HIDDEN" in got and "RO" in got, got
    assert "DEVOK" in got, got
    client.destroy("tb")
    # the mounts died with the task's namespace: host-side the sandbox
    # mount points are plain empty dirs
    assert os.listdir(os.path.join(croot, "usr")) == []


@pytest.mark.skipif(not IS_ROOT, reason="bind sandbox needs root")
def test_executor_task_dir_contract_in_chroot(client, tmp_path):
    """The task-dir env contract resolves inside the sandbox: the
    shared alloc dir is bind-mounted at /alloc, the env vars are
    remapped in-chroot, and writes land in the host's shared dir
    (reference alloc_dir_linux.go mountSharedDir)."""
    alloc = tmp_path / "a1"
    shared = alloc / "alloc" / "data"
    local = alloc / "web" / "local"
    secrets = alloc / "web" / "secrets"
    for d in (shared, local, secrets):
        d.mkdir(parents=True)
    (secrets / "token").write_text("s3cret")
    out = str(tmp_path / "out.txt")
    info = client.launch(
        {
            "task_id": "td",
            "argv": [
                "/bin/sh",
                "-c",
                'echo "$NOMAD_ALLOC_DIR $NOMAD_TASK_DIR '
                '$NOMAD_SECRETS_DIR";'
                ' echo hi > "$NOMAD_ALLOC_DIR/data/shared.txt";'
                ' cat "$NOMAD_SECRETS_DIR/token"',
            ],
            "env": {
                "NOMAD_ALLOC_DIR": str(alloc / "alloc"),
                "NOMAD_TASK_DIR": str(local),
                "NOMAD_SECRETS_DIR": str(secrets),
                "PATH": "/bin:/usr/bin",
            },
            "chroot": str(local),
            "chroot_populate": "bind",
            "task_mounts": [
                [str(alloc / "alloc"), "alloc"],
                [str(local), "local"],
                [str(secrets), "secrets"],
            ],
            "stdout_path": out,
        }
    )
    assert info["isolation"]["chroot"]
    res = client.wait("td", timeout=10)
    assert res["exit_code"] == 0
    got = open(out).read()
    # env remapped to in-chroot paths
    assert got.splitlines()[0] == "/alloc /local /secrets", got
    # the secrets bind resolved
    assert "s3cret" in got
    # the write through /alloc landed in the HOST shared dir
    assert (shared / "shared.txt").read_text().strip() == "hi"
    client.destroy("td")


def test_executor_rotates_logs(client, tmp_path):
    """With a logs dir, the executor pumps output through size-rotated
    logmon files instead of one unbounded flat file."""
    logs = str(tmp_path / "logs")
    client.launch(
        {
            "task_id": "tlog",
            # ~3MB of output against a 1MB cap -> several rotations
            "argv": [
                "/bin/sh",
                "-c",
                "i=0; while [ $i -lt 48 ]; do"
                " head -c 65536 /dev/zero | tr '\\0' 'x'; i=$((i+1));"
                " done",
            ],
            "logs_dir": logs,
            "log_name": "main",
            "log_max_files": 2,
            "log_max_file_size_mb": 1,
        }
    )
    res = client.wait("tlog", timeout=15)
    assert res["exit_code"] == 0
    files = sorted(os.listdir(logs))
    stdout_files = [f for f in files if f.startswith("main.stdout")]
    assert len(stdout_files) >= 2, files
    # max_files enforced and each file capped at ~1MB
    assert len(stdout_files) <= 2
    for f in stdout_files:
        assert os.path.getsize(os.path.join(logs, f)) <= 1100 * 1024
    client.destroy("tlog")


def test_link_command_env_closure(tmp_path):
    env = link_command_env(str(tmp_path), "/bin/sh")
    # the binary (or its symlink chain head) plus the loader
    assert "/bin/sh" in env
    assert any("ld-linux" in p or "ld.so" in p for p in env), env
    build_chroot(str(tmp_path / "root"), env)
    assert os.path.lexists(str(tmp_path / "root" / "bin" / "sh"))


# ---------------------------------------------------------------------------
# driver integration
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not IS_ROOT, reason="isolated exec needs root")
def test_exec_driver_runs_chrooted_task(tmp_path):
    from nomad_tpu.client.drivers import ExecDriver

    marker = tmp_path / "secret"
    marker.write_text("x")
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    d = ExecDriver()
    cfg = TaskConfig(
        id="chroot-task",
        name="main",
        alloc_dir=str(tmp_path),
        task_dir=str(task_dir),
        config={
            "command": "/bin/sh",
            "args": [
                "-c",
                f"test -e {marker} && echo VISIBLE || echo HIDDEN",
            ],
        },
        resources=Resources(cpu=100, memory_mb=64),
    )
    handle = d.start_task(cfg)
    res = handle.wait(timeout=10)
    assert res is not None and res.exit_code == 0
    with open(tmp_path / "main.stdout") as f:
        assert "HIDDEN" in f.read()
    d.destroy_task("chroot-task", force=True)


def test_recover_reports_real_exit_after_executor_reaped(tmp_path):
    """Executor self-reaped (15s idle) before the client came back:
    recovery must report the persisted exit status, not 'lost' — a
    finished batch task must never be re-run (ADVICE r3)."""
    from nomad_tpu.client import executor as ex
    from nomad_tpu.client.drivers import ExecDriver

    d = ExecDriver()
    cfg = TaskConfig(
        id="reap-task",
        name="main",
        alloc_dir=str(tmp_path),
        task_dir=str(tmp_path),
        config={"command": "/bin/sh", "args": ["-c", "exit 7"]},
        resources=Resources(cpu=100, memory_mb=64),
    )
    handle = d.start_task(cfg)
    res = handle.wait(timeout=10)
    assert res is not None and res.exit_code == 7
    # simulate the idle self-reap racing a slow client restart: the
    # executor dies, the reattach record stays
    client = d._clients["reap-task"]
    client.proc.kill()
    client.proc.wait()
    d2 = ExecDriver()
    assert d2.recover_task(
        "reap-task", {"pid": handle.pid}
    ), "recovery must succeed from the persisted exit record"
    res2 = d2.handles["reap-task"].wait(timeout=5)
    assert res2 is not None and res2.exit_code == 7
    assert ex.load_reattach("reap-task") is None


def test_exec_driver_reattach_across_restart(tmp_path):
    """The executor process survives a driver 'restart'; a fresh driver
    recovers the running task from the reattach record (reference
    go-plugin ReattachConfig + RecoverTask)."""
    from nomad_tpu.client.drivers import ExecDriver

    task_dir = tmp_path / "task"
    task_dir.mkdir()
    d1 = ExecDriver()
    cfg = TaskConfig(
        id="reattach-task",
        name="main",
        alloc_dir=str(tmp_path),
        task_dir=str(task_dir),
        config={
            "command": "/bin/sh",
            "args": ["-c", "sleep 120"],
            "chroot": False,
        },
    )
    handle = d1.start_task(cfg)
    assert handle.is_running()
    # simulate a client restart: a brand-new driver instance
    d2 = ExecDriver()
    assert d2.recover_task("reattach-task", {"task_id": "reattach-task"})
    d2.stop_task("reattach-task", timeout=2.0)
    res = d2.handles["reattach-task"].wait(timeout=5)
    assert res is not None and res.signal == 15
    d2.destroy_task("reattach-task", force=True)
