"""Cluster-scope observability tests: cross-server trace segment
export/absorb, redelivery-supersedes across servers (a late segment
from a dead follower lands in the settled old-generation trace, never
the redelivered attempt), explicit ``revoked``/``shed`` outcomes for
traces that used to dangle, the metric time-series history ring, the
3-server fan-out trace-stitching soak, and the leader fan-in HTTP
surface with partial-result (unreachable peer) marking."""
import json
import pickle
import time
import urllib.error
import urllib.request

from types import SimpleNamespace

from nomad_tpu import mock
from nomad_tpu.server.cluster import TestCluster
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.server.overload import MODE_SHEDDING, OverloadController
from nomad_tpu.structs import Evaluation, new_id
from nomad_tpu.telemetry import Metrics, MetricsHistory
from nomad_tpu.trace import TRACE, Tracer

SCHEDS = ["service", "batch", "system", "_core"]


def wait_until(cond, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


def _nodes(n, prefix="obs-node"):
    return [mock.node(id=f"{prefix}-{i:03d}") for i in range(n)]


def _jobs(n, prefix="obs-job"):
    out = []
    for i in range(n):
        job = mock.job(id=f"{prefix}-{i:04d}")
        job.task_groups[0].count = 1
        for tg in job.task_groups:
            for task in tg.tasks:
                task.resources.cpu = 50
                task.resources.memory_mb = 32
        out.append(job)
    return out


def _evals(n, family="obsfam"):
    return [
        Evaluation(
            id=new_id(),
            namespace="default",
            job_id=f"{family}/dispatch-{i:03d}",
            type="batch",
            priority=50,
        )
        for i in range(n)
    ]


def _assert_well_nested(trace):
    """Every span's parent exists and encloses it (small epsilon for
    float math); no orphan (never-closed) spans."""
    assert trace["orphans"] == 0, trace
    by_id = {s["id"]: s for s in trace["spans"]}
    eps = 1e-3  # ms
    for span in trace["spans"]:
        assert span["dur_ms"] is not None, span
        parent = span["parent"]
        if parent is None:
            continue
        assert parent in by_id, span
        p = by_id[parent]
        assert span["off_ms"] >= p["off_ms"] - eps, (span, p)
        assert (
            span["off_ms"] + span["dur_ms"]
            <= p["off_ms"] + p["dur_ms"] + eps
        ), (span, p)


def _lanes(trace):
    """Distinct server_id values across a trace's spans (None = the
    server that owns the trace)."""
    return {
        (s.get("attrs") or {}).get("server_id")
        for s in trace["spans"]
    }


# -- segment export / absorb (two tracers = two "processes") ----------


def test_segment_export_absorb_stitches_remote_spans():
    """The leader's trace and a follower's segment live in different
    tracers (different processes in a real deployment); the shipped
    segment re-anchors onto the leader's clock, carries the follower's
    server_id on every span, and the ship marker itself is visible."""
    leader = Tracer(ring=8)
    follower = Tracer(ring=8)
    leader.begin("ev-seg", queue="service")
    ctx = leader.export_context("ev-seg")
    assert ctx is not None and "#" in ctx["trace_id"]

    follower.begin_segment("ev-seg", ctx)
    with follower.span("ev-seg", "batch_worker.simulate"):
        with follower.span("ev-seg", "batch_worker.assemble", members=2):
            pass
    follower.annotate("ev-seg", outcome="speculative")
    seg = follower.export_segment("ev-seg", "srv-b", close=True)
    assert seg is not None
    assert seg["trace_id"] == ctx["trace_id"]
    assert seg["server_id"] == "srv-b"
    assert follower.open_segments() == 0

    absorbed = leader.absorb_segment(seg)
    assert absorbed >= 3  # simulate + assemble + ship marker
    leader.finish("ev-seg", "ack")
    trace = leader.get("ev-seg")
    assert trace["complete"]
    # the follower's richer outcome annotation traveled in the
    # segment and was consumed by the ack
    assert trace["outcome"] == "speculative"
    _assert_well_nested(trace)
    by_name = {s["name"]: s for s in trace["spans"]}
    assert by_name["batch_worker.simulate"]["attrs"]["server_id"] == (
        "srv-b"
    )
    assert "fanout.remote_span_ship" in by_name
    # intra-batch parent links survive the sid remap
    assert by_name["batch_worker.assemble"]["parent"] == (
        by_name["batch_worker.simulate"]["id"]
    )


def test_killed_follower_late_segment_lands_in_superseded_trace():
    """Redelivery supersedes ACROSS servers: a segment straggling in
    from a dead follower carries the old generation's trace id and
    must land in that settled trace — never interleave into the
    redelivered attempt's trace."""
    leader = Tracer(ring=8)
    dead = Tracer(ring=8)
    leader.begin("ev-kill")
    old_ctx = leader.export_context("ev-kill")
    dead.begin_segment("ev-kill", old_ctx)
    with dead.span("ev-kill", "batch_worker.simulate"):
        pass
    # follower dies mid-lease; the leader reclaims and redelivers,
    # which begins a NEW generation and settles the old one
    leader.begin("ev-kill")
    leader.finish("ev-kill", "ack")
    new_trace = leader.get("ev-kill")
    assert new_trace["outcome"] == "ack"

    # the dead follower's segment finally arrives (stale token path
    # absorbs the segment before rejecting the settle)
    seg = dead.export_segment("ev-kill", "dead-f", close=True)
    assert leader.absorb_segment(seg) >= 1

    traces = {
        t["trace_id"]: t
        for t in leader.recent(limit=10, full=True)
        if t["eval_id"] == "ev-kill"
    }
    assert len(traces) == 2
    old = traces[old_ctx["trace_id"]]
    new = leader.get("ev-kill")
    assert old["outcome"] == "superseded"
    old_names = {s["name"] for s in old["spans"]}
    new_names = {s["name"] for s in new["spans"]}
    assert "batch_worker.simulate" in old_names
    assert "batch_worker.simulate" not in new_names
    assert "dead-f" not in _lanes(new)


def test_local_redelivery_evicts_stale_segment():
    """If the lease is reclaimed and redelivered to THIS server, the
    next recording call drops the stale segment ('superseded') instead
    of swallowing the new attempt's spans."""
    t = Tracer(ring=8)
    t.begin("ev-loc")
    ctx = t.export_context("ev-loc")
    t.begin_segment("ev-loc", ctx)
    assert t.open_segments() == 1
    t.begin("ev-loc")  # redelivered locally: new trace id
    with t.span("ev-loc", "batch_worker.sequential"):
        pass
    assert t.open_segments() == 0
    t.finish("ev-loc", "ack")
    trace = t.get("ev-loc")
    assert {s["name"] for s in trace["spans"]} == {
        "broker.dequeue",
        "batch_worker.sequential",
    }


# -- explicit outcomes for formerly-dangling traces -------------------


def test_broker_flush_finishes_unacked_traces_revoked():
    """A leadership revoke flushes the broker; every unacked
    delivery's trace settles with an explicit `revoked` outcome
    instead of dangling 'in flight' forever."""
    TRACE.clear()
    broker = EvalBroker(nack_timeout=60.0)
    broker.set_enabled(True)
    evs = _evals(3)
    broker.enqueue_all(evs)
    leases = broker.dequeue_remote(
        ["batch"], timeout=1.0, max_n=3, peer="server-9"
    )
    assert len(leases) == 3
    for ev, _tok in leases:
        assert TRACE.get(ev.id)["complete"] is False
    broker.set_enabled(False)  # revoke -> flush
    for ev, _tok in leases:
        trace = TRACE.get(ev.id)
        assert trace["complete"], trace
        assert trace["outcome"] == "revoked"
    TRACE.clear()


def test_overload_close_incident_finishes_shed_trace():
    """Server shutdown mid-incident settles the incident trace with
    an explicit `shed` outcome and the shed-count annotation."""
    TRACE.clear()
    ctl = OverloadController(SimpleNamespace(metrics=Metrics()))
    with ctl._lock:
        ctl._transition_locked(MODE_SHEDDING, 999.0, 45.0, 0.0)
    incident = ctl._incident_id
    assert incident is not None
    assert TRACE.get(incident)["complete"] is False
    ctl.close_incident()
    assert ctl._incident_id is None
    trace = TRACE.get(incident)
    assert trace["complete"]
    assert trace["outcome"] == "shed"
    assert "shed_total" in trace["attrs"]
    ctl.close_incident()  # idempotent
    TRACE.clear()


# -- metric time-series history ---------------------------------------


def test_metrics_history_ring_bounded_with_percentiles():
    m = Metrics()
    m.preregister(
        counters=("obs.history_snapshots",),
        gauges=("obs.history_windows",),
    )
    hist = MetricsHistory(m, windows=4, interval_s=60.0)
    for round_no in range(6):
        m.incr("test.ticks")
        for v in range(10):
            m.add_sample("test.lat_ms", float(v + round_no))
        hist.snapshot_once()
    out = hist.to_dict()
    assert out["enabled"] is True
    assert out["max_windows"] == 4
    assert len(out["windows"]) == 4  # ring bounded
    window = out["windows"][-1]
    assert window["counters"]["test.ticks"] == 6.0
    sample = window["samples"]["test.lat_ms"]
    assert set(sample) == {"count", "p50", "p99"}
    assert m.get_gauge("obs.history_windows") == 4.0
    assert m.get_counter("obs.history_snapshots") == 6.0
    series = hist.series("test.lat_ms")
    assert len(series) == 4
    assert all("p99" in point for point in series)
    counter_series = hist.series("test.ticks")
    assert [p["value"] for p in counter_series] == [3.0, 4.0, 5.0, 6.0]
    assert hist.series("nope") == []


def test_metrics_history_thread_snapshots(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_OBS_HISTORY_N", "8")
    m = Metrics()
    hist = MetricsHistory(m, interval_s=0.05)
    hist.start()
    try:
        wait_until(
            lambda: len(hist.to_dict()["windows"]) >= 2,
            timeout=10.0,
            msg="history snapshots",
        )
    finally:
        hist.stop()
    assert hist.to_dict()["max_windows"] == 8


def test_metrics_history_disabled_knob(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_OBS_HISTORY", "0")
    hist = MetricsHistory(Metrics())
    hist.start()
    hist.stop()
    out = hist.to_dict()
    assert out["enabled"] is False
    assert out["windows"] == []


# -- 3-server fan-out trace-stitching soak ----------------------------


def test_fanout_trace_stitching_soak(monkeypatch):
    """Every completed eval in a 3-server fan-out run carries a
    well-nested dequeue->commit trace on the leader; follower-planned
    evals stitch spans from >= 2 distinct servers into ONE waterfall;
    zero orphan spans and zero dangling segments after drain."""
    monkeypatch.setenv("NOMAD_TPU_FANOUT", "1")
    TRACE.clear()
    n_jobs = 12
    cluster = TestCluster(3, heartbeat_ttl=300.0)
    cluster.start()
    try:
        leader = cluster.wait_for_leader(timeout=30.0)
        for node in _nodes(12):
            leader.register_node(node)
        evs = []
        for i, job in enumerate(_jobs(n_jobs)):
            evs.append(cluster.servers[i % 3].register_job(job))
        wait_until(
            lambda: cluster.wait_for_leader(timeout=30.0)
            .drain_to_idle(timeout=1.0),
            timeout=90.0,
            msg="fan-out drain",
        )
        leader = cluster.wait_for_leader(timeout=30.0)
        shipped = sum(
            s.metrics.get_counter("fanout.segments_shipped")
            for s in cluster.servers
        )
        assert shipped > 0, "no trace segments ever shipped"
        assert leader.metrics.get_counter("cluster.segments_absorbed") > 0

        stitched = 0
        completed = 0
        for ev in evs:
            trace = TRACE.get(ev.id)
            assert trace is not None, ev.id
            if not trace["complete"]:
                continue
            completed += 1
            _assert_well_nested(trace)
            names = [s["name"] for s in trace["spans"]]
            assert names[0] == "broker.dequeue", names
            lanes = _lanes(trace)
            if len(lanes) >= 2:
                stitched += 1
                assert "fanout.remote_span_ship" in names
                assert "store.commit" in names
        assert completed == n_jobs, (completed, n_jobs)
        assert stitched > 0, "no stitched cross-server trace"
        # zero orphan segments: every follower buffer was shipped on
        # settle or evicted by redelivery
        wait_until(
            lambda: TRACE.open_segments() == 0,
            timeout=10.0,
            msg="segments drained",
        )
    finally:
        cluster.stop()
        TRACE.clear()


def test_fanout_follower_kill_redelivery_supersedes_over_rpc(
    monkeypatch,
):
    """The integration shape of redelivery-supersedes: a follower
    leases over the real RPC surface, records into its segment, dies;
    the leader reclaims + redelivers (new trace generation); the dead
    follower's straggler settle RPC still ships its segment, which
    lands in the OLD generation's trace."""
    TRACE.clear()
    cluster = TestCluster(
        3, heartbeat_ttl=300.0, nack_timeout=0.5, num_schedulers=0
    )
    cluster.start()
    try:
        leader = cluster.wait_for_leader(timeout=30.0)
        follower = cluster.followers()[0]
        other = cluster.followers()[1]
        leader.broker.enqueue_all(_evals(2, family="kill"))
        resp = cluster.transport.rpc(
            follower.addr,
            leader.addr,
            "broker_dequeue",
            {
                "schedulers": SCHEDS,
                "timeout": 1.0,
                "n": 2,
                "server": follower.addr,
            },
        )
        leases = pickle.loads(resp["leases"])
        assert len(leases) == 2
        ctxs = resp.get("trace_ctx") or {}
        ev, token = leases[0]
        old_ctx = ctxs[ev.id]
        # the "follower" records pipeline spans into its segment
        TRACE.begin_segment(ev.id, old_ctx)
        with TRACE.span(ev.id, "batch_worker.simulate"):
            pass
        # follower dies: never settles; leader reclaims at the nack
        # timeout and redelivers to another server
        wait_until(
            lambda: leader.broker.remote_unacked_count() == 0,
            timeout=10.0,
            msg="lease reclamation",
        )
        resp2 = cluster.transport.rpc(
            other.addr,
            leader.addr,
            "broker_dequeue",
            {
                "schedulers": SCHEDS,
                "timeout": 1.0,
                "n": 2,
                "server": other.addr,
            },
        )
        redelivered = {
            e.id: ctx_tok
            for e, ctx_tok in pickle.loads(resp2["leases"])
        }
        assert ev.id in redelivered
        new_ctx = (resp2.get("trace_ctx") or {})[ev.id]
        assert new_ctx["trace_id"] != old_ctx["trace_id"]
        # the dead follower's straggler settle finally arrives with
        # the OLD token: the segment is absorbed (old generation),
        # the ack itself is rejected
        seg = TRACE.export_segment(ev.id, follower.addr, close=True)
        assert seg is not None
        try:
            cluster.transport.rpc(
                follower.addr,
                leader.addr,
                "broker_ack",
                {"eval_id": ev.id, "token": token, "segment": seg},
            )
        except Exception:
            pass  # stale-token rejection is expected
        assert TRACE.open_segments() == 0
        traces = {
            t["trace_id"]: t
            for t in TRACE.recent(limit=16, full=True)
            if t["eval_id"] == ev.id
        }
        old = traces.get(old_ctx["trace_id"])
        assert old is not None
        # the sweeper nacks the reclaimed lease (settling the old
        # generation) before the redelivery begins the new one
        assert old["outcome"] in ("nack", "superseded")
        assert "batch_worker.simulate" in {
            s["name"] for s in old["spans"]
        }
        new = TRACE.get(ev.id)
        assert new["trace_id"] == new_ctx["trace_id"]
        assert "batch_worker.simulate" not in {
            s["name"] for s in new["spans"]
        }
    finally:
        cluster.stop()
        TRACE.clear()


# -- leader fan-in HTTP surface ---------------------------------------


def _get_json(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return json.loads(resp.read())


def test_cluster_http_endpoints(monkeypatch):
    """/v1/cluster/* fan the query out to every peer and merge;
    unreachable peers are marked per-server instead of failing the
    whole query; /v1/metrics/history serves the ring."""
    from nomad_tpu.api import start_http_server

    monkeypatch.setenv("NOMAD_TPU_FANOUT", "1")
    monkeypatch.setenv("NOMAD_TPU_OBS_FANIN_TIMEOUT_S", "2.0")
    TRACE.clear()
    cluster = TestCluster(3, heartbeat_ttl=300.0)
    cluster.start()
    http = None
    try:
        leader = cluster.wait_for_leader(timeout=30.0)
        for node in _nodes(8, prefix="ch-node"):
            leader.register_node(node)
        evs = []
        for i, job in enumerate(_jobs(6, prefix="ch-job")):
            evs.append(cluster.servers[i % 3].register_job(job))
        wait_until(
            lambda: cluster.wait_for_leader(timeout=30.0)
            .drain_to_idle(timeout=1.0),
            timeout=90.0,
            msg="drain",
        )
        leader = cluster.wait_for_leader(timeout=30.0)
        http = start_http_server(leader, port=0)
        base = f"http://127.0.0.1:{http.port}"

        listing = _get_json(base, "/v1/cluster/traces?limit=64")
        assert listing["unreachable"] == 0
        assert set(listing["servers"].values()) == {"ok"}
        assert len(listing["servers"]) == 3
        listed = {t["eval_id"] for t in listing["traces"]}
        for ev in evs:
            assert ev.id in listed
        # the merged listing is deduplicated by trace id
        assert len(listed) == len(listing["traces"])
        assert all(t.get("server") for t in listing["traces"])

        detail = _get_json(base, f"/v1/cluster/traces/{evs[0].id}")
        assert detail["complete"]
        assert detail["server"]
        assert set(detail["servers"].values()) == {"ok"}
        assert any(
            s["name"] == "store.commit" for s in detail["spans"]
        )
        try:
            urllib.request.urlopen(
                base + "/v1/cluster/traces/nope", timeout=10
            )
            assert False, "expected 404"
        except urllib.error.HTTPError as exc:
            assert exc.code == 404

        # metric history: snapshot deterministically, then read back
        leader.metrics_history.snapshot_once()
        leader.metrics_history.snapshot_once()
        hist = _get_json(base, "/v1/metrics/history")
        assert hist["enabled"] is True
        assert len(hist["windows"]) >= 2
        assert "batch_worker.eval_latency_ms" in (
            hist["windows"][-1]["samples"]
        )
        series = _get_json(
            base,
            "/v1/metrics/history?name=batch_worker.eval_latency_ms",
        )
        assert series["name"] == "batch_worker.eval_latency_ms"
        assert all("p99" in p for p in series["series"])

        merged = _get_json(base, "/v1/cluster/metrics")
        assert merged["unreachable"] == 0
        assert len(merged["servers"]) == 3
        for data in merged["servers"].values():
            assert "counters" in data
        hist_all = _get_json(base, "/v1/cluster/metrics/history")
        assert len(hist_all["servers"]) == 3

        # partial results: a peer that cannot be reached is MARKED,
        # not silently dropped and not fatal
        down = cluster.followers()[0].addr
        cluster.transport.set_down(down)
        try:
            merged = _get_json(base, "/v1/cluster/metrics")
            assert merged["unreachable"] == 1
            assert merged["servers"][down] == {"unreachable": True}
            listing = _get_json(base, "/v1/cluster/traces?limit=8")
            assert listing["servers"][down] == "unreachable"
        finally:
            cluster.transport.set_down(down, down=False)
        assert (
            leader.metrics.get_counter("cluster.fanin_unreachable")
            >= 2.0
        )
        assert (
            leader.metrics.get_counter("cluster.fanin_queries") > 0
        )
    finally:
        if http is not None:
            http.stop()
        cluster.stop()
        TRACE.clear()
