"""Global storm solver tests (NOMAD_TPU_STORM=1): the broker's
atomic family drain, the device-side assignment solve, and the
decompose-and-commit path.

Contracts under test:

- ``drain_family`` dequeues the contiguous pop-order prefix of one
  job family — never leapfrogging unrelated evals, all-or-nothing
  below its threshold, full unack/token bookkeeping per member.
- Degenerate parity: a single-eval storm (threshold forced to 1)
  produces bit-identical placements and AllocMetrics to the serial
  chain — the solver's one-row assignment IS the greedy walk.
- A mass family storm places every eval with zero losses, commits
  through the existing conflict fences, and tags every solver-placed
  eval's explain record with the auditable ``Storm`` block.
- Ineligible members and solve failures fall back to the normal
  batch path inside the same FIFO order — correctness never depends
  on the solver.
- ``NOMAD_TPU_STORM=0`` (the default) never engages any of it.
"""
import copy
import random

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.server import EvalBroker, Server
from nomad_tpu.server.eval_broker import job_family
from nomad_tpu.structs import compute_node_class


# ---------------------------------------------------------------------------
# job_family
# ---------------------------------------------------------------------------


def test_job_family_collapses_children():
    base = mock.evaluation(job_id="ingest", namespace="default")
    disp = mock.evaluation(
        job_id="ingest/dispatch-1723-abcd", namespace="default"
    )
    peri = mock.evaluation(
        job_id="ingest/periodic-1723", namespace="default"
    )
    other_ns = mock.evaluation(job_id="ingest", namespace="prod")
    assert job_family(base) == ("default", "ingest")
    assert job_family(disp) == job_family(base)
    assert job_family(peri) == job_family(base)
    assert job_family(other_ns) != job_family(base)
    assert job_family(mock.evaluation(job_id="other")) != job_family(
        base
    )


# ---------------------------------------------------------------------------
# drain_family
# ---------------------------------------------------------------------------


def _mk_broker(**kw):
    b = EvalBroker(**kw)
    b.set_enabled(True)
    return b


def _fam_eval(i, fam="fam", priority=50):
    return mock.evaluation(
        job_id=f"{fam}/dispatch-{i:04d}", priority=priority
    )


def test_drain_family_contiguous_prefix_no_leapfrog():
    b = _mk_broker()
    front = [_fam_eval(i) for i in range(3)]
    stranger = mock.evaluation(job_id="other-job")
    tail = [_fam_eval(i) for i in range(3, 5)]
    for ev in front + [stranger] + tail:
        b.enqueue(ev)
    out = b.drain_family(
        ["service"], ("default", "fam"), max_n=10
    )
    # the walk stops at the first unrelated ready eval: the two
    # family members QUEUED BEHIND the stranger are not leapfrogged
    assert [ev.id for ev, _t in out] == [ev.id for ev in front]
    nxt, tok = b.dequeue(["service"], timeout=1)
    assert nxt is stranger
    b.ack(nxt.id, tok)
    for want in tail:
        ev, tok = b.dequeue(["service"], timeout=1)
        assert ev is want
        b.ack(ev.id, tok)
    for ev, tok in out:
        b.ack(ev.id, tok)
    assert b.stats["total_ready"] == 0
    assert b.stats["total_unacked"] == 0


def test_drain_family_respects_max_n():
    b = _mk_broker()
    evs = [_fam_eval(i) for i in range(6)]
    for ev in evs:
        b.enqueue(ev)
    out = b.drain_family(["service"], ("default", "fam"), max_n=4)
    assert [ev.id for ev, _t in out] == [ev.id for ev in evs[:4]]
    # remainder still ready, in order
    ev, tok = b.dequeue(["service"], timeout=1)
    assert ev is evs[4]
    b.nack(ev.id, tok)
    for e, t in out:
        b.ack(e.id, t)


def test_drain_family_all_or_nothing_below_min():
    b = _mk_broker()
    evs = [_fam_eval(i) for i in range(2)]
    for ev in evs:
        b.enqueue(ev)
    assert (
        b.drain_family(
            ["service"], ("default", "fam"), max_n=10, min_n=3
        )
        == []
    )
    # nothing was dequeued and FIFO order is untouched
    assert b.stats["total_ready"] == 2
    assert b.stats["total_unacked"] == 0
    for want in evs:
        ev, tok = b.dequeue(["service"], timeout=1)
        assert ev is want
        b.ack(ev.id, tok)


def test_drain_family_priority_fences_the_prefix():
    """A higher-priority unrelated eval pops first, so it FENCES the
    drain even though family members are queued: the family is not
    the pop-order prefix."""
    b = _mk_broker()
    for i in range(3):
        b.enqueue(_fam_eval(i))
    vip = mock.evaluation(job_id="vip", priority=90)
    b.enqueue(vip)
    assert (
        b.drain_family(["service"], ("default", "fam"), max_n=10)
        == []
    )
    ev, tok = b.dequeue(["service"], timeout=1)
    assert ev is vip
    b.ack(ev.id, tok)


def test_drain_family_token_bookkeeping_and_nack():
    b = _mk_broker(delivery_limit=5)
    evs = [_fam_eval(i) for i in range(4)]
    for ev in evs:
        b.enqueue(ev)
    out = b.drain_family(["service"], ("default", "fam"), max_n=10)
    assert len(out) == 4
    assert b.stats["total_unacked"] == 4
    # a stale token is rejected exactly like dequeue()'s
    with pytest.raises(ValueError):
        b.ack(out[0][0].id, "bogus-token")
    # ack half, nack half: nacked members re-enqueue and redeliver
    for ev, tok in out[:2]:
        b.ack(ev.id, tok)
    for ev, tok in out[2:]:
        b.nack(ev.id, tok)
    redelivered = []
    for _ in range(2):
        ev, tok = b.dequeue(["service"], timeout=1)
        redelivered.append(ev.id)
        b.ack(ev.id, tok)
    assert sorted(redelivered) == sorted(ev.id for ev, _t in out[2:])
    assert b.stats["total_unacked"] == 0


def test_drain_family_nack_timeout_redelivers():
    b = _mk_broker(nack_timeout=0.1, delivery_limit=5)
    for i in range(2):
        b.enqueue(_fam_eval(i))
    out = b.drain_family(["service"], ("default", "fam"), max_n=10)
    assert len(out) == 2
    # never ack: the sweeper must nack both for us
    got = set()
    for _ in range(2):
        ev, tok = b.dequeue(["service"], timeout=3)
        assert ev is not None
        got.add(ev.id)
        b.ack(ev.id, tok)
    assert got == {ev.id for ev, _t in out}


# ---------------------------------------------------------------------------
# ops/solve.py unit level
# ---------------------------------------------------------------------------


def _solver_problem(E, A, C, ask=(100.0, 100.0, 100.0), limit=2,
                    seed=0, shared_perm=False):
    import jax.numpy as jnp

    from nomad_tpu.ops.solve import StormInputs

    rng = np.random.default_rng(seed)
    if shared_perm:
        perm = np.tile(
            rng.permutation(C).astype(np.int32), (E, 1)
        )
    else:
        perm = np.stack(
            [rng.permutation(C).astype(np.int32) for _ in range(E)]
        )
    inp = StormInputs(
        feasible=np.ones((E, C), bool),
        affinity=np.zeros((E, C), np.float32),
        collisions=np.zeros((E, C), np.int32),
        perm=perm,
        limit=np.full(E, limit, np.int32),
        n_cand=np.full(E, C, np.int32),
        eval_of=(np.arange(A) % E).astype(np.int32),
        penalty=np.zeros((A, C), bool),
        ask=np.tile(np.asarray(ask, np.float32), (A, 1)),
        desired=np.ones(A, np.int32),
        real=np.ones(A, bool),
        pre_cpu=np.zeros(C, np.float32),
        pre_mem=np.zeros(C, np.float32),
        pre_disk=np.zeros(C, np.float32),
    )
    cols = tuple(
        jnp.asarray(x)
        for x in (
            np.full(C, 4000.0, np.float32),
            np.full(C, 8192.0, np.float32),
            np.full(C, 100000.0, np.float32),
            np.zeros(C, np.float32),
            np.zeros(C, np.float32),
            np.zeros(C, np.float32),
        )
    )
    return inp, cols


def test_solver_assigns_all_and_never_overcommits():
    from nomad_tpu.ops.solve import storm_assignment

    E = A = 32
    C = 16
    # 32 rows of 1000 cpu over 16 nodes of 4000: tight but feasible
    inp, cols = _solver_problem(
        E, A, C, ask=(1000.0, 100.0, 100.0), shared_perm=True
    )
    out = storm_assignment(
        inp, cols, spread_fit=False, max_rounds=A
    )
    assigned = np.asarray(out[0])
    assert (assigned >= 0).all()
    counts = np.bincount(assigned, minlength=C)
    assert counts.max() <= 4  # 4 x 1000 = the node's cpu capacity
    # identical asks dog-piling one shared walk order must still
    # converge in a handful of rounds, not one acceptance at a time
    assert int(out[5]) <= 8


def test_solver_one_row_is_exactly_the_greedy_walk():
    from nomad_tpu.ops.score import (
        ScoreInputs,
        _limited_walk_argmax,
        _score_vectors,
    )
    from nomad_tpu.ops.solve import storm_assignment

    E, A, C = 1, 1, 12
    inp, cols = _solver_problem(E, A, C, limit=3, seed=7)
    assigned, pulls, acc_round, score, greedy, rounds = (
        storm_assignment(inp, cols, spread_fit=False, max_rounds=4)
    )
    # the oracle: the serial chain's limited walk over the same score
    # vectors
    si = ScoreInputs(
        cpu_total=cols[0], mem_total=cols[1], disk_total=cols[2],
        cpu_used=cols[3], mem_used=cols[4], disk_used=cols[5],
        feasible=np.ones((1, C), bool),
        collisions=np.zeros((1, C), np.int32),
        penalty=np.zeros((1, C), bool),
        affinity_score=np.zeros((1, C), np.float32),
        spread_boost=np.zeros((), np.float32),
        perm=inp.perm,
        ask_cpu=inp.ask[:, 0:1],
        ask_mem=inp.ask[:, 1:2],
        ask_disk=inp.ask[:, 2:3],
        desired_count=inp.desired[:, None],
        limit=inp.limit,
        n_candidates=inp.n_cand,
    )
    import jax

    feas, scores = _score_vectors(si, False)
    want_row, _best, _nf, want_pulls = jax.vmap(
        _limited_walk_argmax
    )(feas, scores, si.perm, si.limit, si.n_candidates)
    assert int(assigned[0]) == int(want_row[0])
    assert int(assigned[0]) == int(greedy[0])
    assert int(pulls[0]) == int(want_pulls[0])
    assert int(acc_round[0]) == 0


def test_solver_padding_rows_never_assigned():
    from nomad_tpu.ops.solve import storm_assignment

    E, A, C = 4, 8, 8
    inp, cols = _solver_problem(E, A, C)
    real = np.ones(A, bool)
    real[5:] = False
    inp = inp._replace(real=real)
    out = storm_assignment(
        inp, cols, spread_fit=False, max_rounds=A
    )
    assigned = np.asarray(out[0])
    assert (assigned[5:] == -1).all()
    assert (assigned[:5] >= 0).all()


def test_solver_infeasible_row_returns_no_node():
    from nomad_tpu.ops.solve import storm_assignment

    E, A, C = 2, 2, 8
    inp, cols = _solver_problem(E, A, C)
    feasible = np.ones((E, C), bool)
    feasible[1, :] = False
    inp = inp._replace(feasible=feasible)
    out = storm_assignment(
        inp, cols, spread_fit=False, max_rounds=A
    )
    assigned = np.asarray(out[0])
    assert int(assigned[0]) >= 0
    assert int(assigned[1]) == -1
    assert int(np.asarray(out[2])[1]) == -1  # acc_round unsolved


# ---------------------------------------------------------------------------
# server level
# ---------------------------------------------------------------------------


def make_nodes(n, seed=3):
    rng = random.Random(seed)
    nodes = []
    for i in range(n):
        node = mock.node(id=f"storm-node-{seed}-{i:04d}")
        node.node_resources.cpu = rng.choice([8000, 16000])
        node.node_resources.memory_mb = rng.choice([16384, 32768])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    return nodes


def family_jobs(n, fam="stfam", count=1, cpu=2000):
    jobs = []
    for i in range(n):
        job = mock.job(id=f"{fam}/dispatch-{i:04d}")
        job.type = "batch"
        job.task_groups[0].count = count
        job.task_groups[0].tasks[0].resources.cpu = cpu
        job.task_groups[0].tasks[0].resources.memory_mb = 4096
        jobs.append(job)
    return jobs


def run_storm_server(jobs, n_nodes=24, nodes_seed=3, timeout=120):
    """Jobs registered BEFORE leadership, so the whole family lands
    in the broker as one restore wave — the mass-drain shape."""
    server = Server(num_schedulers=1, seed=11, batch_pipeline=True)
    for node in make_nodes(n_nodes, seed=nodes_seed):
        server.register_node(copy.deepcopy(node))
    for job in jobs:
        server.register_job(copy.deepcopy(job))
    server.start()
    assert server.drain_to_idle(timeout)
    return server


def placements(server, job_id):
    return sorted(
        (a.name, a.node_id)
        for a in server.store.allocs_by_job("default", job_id)
        if not a.terminal_status()
    )


def eval_outcomes(server, job_id):
    return sorted(
        (
            e.status,
            e.status_description,
            tuple(sorted(e.queued_allocations.items())),
        )
        for e in server.store.evals_by_job("default", job_id)
    )


def assert_zero_lost(server, jobs):
    for job in jobs:
        evs = server.store.evals_by_job("default", job.id)
        assert evs, f"no evals for {job.id}"
        assert all(e.terminal_status() for e in evs), (
            f"non-terminal eval for {job.id}"
        )
    assert server.broker.failed() == []


def explain_metric(server, job_id):
    """Comparable AllocMetric view from the explain ring (wall-clock
    fields and the storm audit annotation stripped)."""
    from nomad_tpu.explain import EXPLAIN

    out = []
    for ev in sorted(
        server.store.evals_by_job("default", job_id),
        key=lambda e: e.create_index,
    ):
        rec = EXPLAIN.get(ev.id)
        if rec is None:
            out.append(None)
            continue
        tgs = {}
        for tg, entry in rec["TaskGroups"].items():
            metric = entry.get("Metric")
            if metric is not None:
                metric = {
                    k: v
                    for k, v in metric.items()
                    if k != "AllocationTime"
                }
            tgs[tg] = {
                "Placed": entry["Placed"],
                "Failed": entry["Failed"],
                "Winner": entry["Winner"],
                "Placements": sorted(
                    (
                        p["Name"],
                        p["NodeID"],
                        round(p["NormScore"], 9),
                    )
                    for p in entry["Placements"]
                ),
                "Metric": metric,
            }
        out.append(tgs)
    return out


def test_storm_mass_family_zero_lost(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_STORM", "1")
    monkeypatch.setenv("NOMAD_TPU_STORM_MIN", "8")
    jobs = family_jobs(24)
    server = run_storm_server(jobs)
    try:
        worker = server.workers[0]
        assert worker.storm_solves >= 1
        assert worker.storm_evals == 24
        total_placed = 0
        for job in jobs:
            p = placements(server, job.id)
            assert len(p) == 1, f"{job.id} placed {len(p)}"
            total_placed += len(p)
        assert total_placed == 24
        assert_zero_lost(server, jobs)
        # counters mirror to /v1/metrics (zero-registered family)
        m = server.metrics
        assert m.get_counter("storm.solves") == worker.storm_solves
        assert m.get_counter("storm.evals") == worker.storm_evals
        assert m.get_counter("storm.rows") == worker.storm_rows
        assert m.get_gauge("storm.rounds") is not None
        assert m.get_gauge("batch_worker.storm_enabled") == 1.0
        # solver wall time feeds its own EWMA bucket, never the
        # chunk-width buckets the adaptive gulp policy plans from
        assert "storm" in worker._launch_ewma
        assert (
            m.get_gauge("batch_worker.launch_ewma_ms.storm")
            is not None
        )
        # every solver-placed eval carries the auditable Storm block
        from nomad_tpu.explain import EXPLAIN

        tagged = 0
        for job in jobs:
            for ev in server.store.evals_by_job("default", job.id):
                rec = EXPLAIN.get(ev.id)
                if rec is None:
                    continue
                storm = rec.get("Storm")
                if storm is not None:
                    tagged += 1
                    assert storm["Round"] >= 0
                    assert storm["Rows"] == 1
                    assert 0 <= storm["DivergentRows"] <= 1
        assert tagged + worker.storm_fallbacks >= 24
        assert tagged > 0
    finally:
        server.stop()


def test_storm_below_threshold_never_engages(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_STORM", "1")
    monkeypatch.setenv("NOMAD_TPU_STORM_MIN", "64")
    jobs = family_jobs(6)
    server = run_storm_server(jobs)
    try:
        worker = server.workers[0]
        assert worker.storm_solves == 0
        assert worker.storm_evals == 0
        for job in jobs:
            assert len(placements(server, job.id)) == 1
        assert_zero_lost(server, jobs)
    finally:
        server.stop()


def test_storm_off_is_inert(monkeypatch):
    monkeypatch.setenv("NOMAD_TPU_STORM", "0")
    monkeypatch.setenv("NOMAD_TPU_STORM_MIN", "1")
    jobs = family_jobs(10)
    server = run_storm_server(jobs)
    try:
        worker = server.workers[0]
        assert not worker.storm_enabled
        assert worker.storm_solves == 0
        assert worker.storm_evals == 0
        assert (
            server.metrics.get_gauge("batch_worker.storm_enabled")
            == 0.0
        )
        assert_zero_lost(server, jobs)
    finally:
        server.stop()


def test_storm_degenerate_single_eval_parity(monkeypatch):
    """The serial-equivalence floor: ONE pending eval forced through
    the solver (threshold=1) must produce bit-identical placements,
    eval outcomes and AllocMetrics to the storm-off chain — the
    solver's one-row assignment is exactly the greedy walk, pulls
    included."""
    jobs = family_jobs(1, fam="degen")
    monkeypatch.setenv("NOMAD_TPU_STORM", "1")
    monkeypatch.setenv("NOMAD_TPU_STORM_MIN", "1")
    on = run_storm_server(jobs)
    try:
        on_metrics = {
            j.id: explain_metric(on, j.id) for j in jobs
        }
        worker = on.workers[0]
        assert worker.storm_solves == 1, "solver did not engage"
        assert worker.storm_fallbacks == 0
        assert worker.storm_divergent == 0
        from nomad_tpu.explain import EXPLAIN

        ev = on.store.evals_by_job("default", jobs[0].id)[0]
        storm_tag = EXPLAIN.get(ev.id).get("Storm")
        assert storm_tag is not None
        assert storm_tag["Round"] == 0
        assert storm_tag["DivergentRows"] == 0
        monkeypatch.setenv("NOMAD_TPU_STORM", "0")
        off = run_storm_server(jobs)
        try:
            off_metrics = {
                j.id: explain_metric(off, j.id) for j in jobs
            }
            for job in jobs:
                assert placements(on, job.id) == placements(
                    off, job.id
                )
                assert eval_outcomes(on, job.id) == eval_outcomes(
                    off, job.id
                )
                assert on_metrics[job.id] == off_metrics[job.id]
        finally:
            off.stop()
    finally:
        on.stop()


def test_storm_ineligible_members_fall_back(monkeypatch):
    """A family whose members the solver cannot model (spread jobs)
    rides the same wave via the serial path: zero lost, everything
    placed, fallbacks counted."""
    from nomad_tpu.structs import Spread, SpreadTarget

    monkeypatch.setenv("NOMAD_TPU_STORM", "1")
    monkeypatch.setenv("NOMAD_TPU_STORM_MIN", "4")
    jobs = family_jobs(10, fam="mixfam")
    for job in jobs[3:6]:
        job.spreads = [
            Spread(
                attribute="${node.datacenter}",
                weight=50,
                targets=(SpreadTarget(value="dc1", percent=100),),
            )
        ]
    server = run_storm_server(jobs)
    try:
        worker = server.workers[0]
        assert worker.storm_evals == 10
        assert worker.storm_fallbacks >= 3
        for job in jobs:
            assert len(placements(server, job.id)) == 1
        assert_zero_lost(server, jobs)
    finally:
        server.stop()


def test_storm_solve_failure_loses_nothing(monkeypatch):
    """The solver crashing mid-storm must degrade to the serial
    chain for every member — zero lost evals, all placed."""
    from nomad_tpu.server.batch_worker import BatchWorker

    monkeypatch.setenv("NOMAD_TPU_STORM", "1")
    monkeypatch.setenv("NOMAD_TPU_STORM_MIN", "4")

    def boom(self, problem, snap):
        raise RuntimeError("injected solve failure")

    monkeypatch.setattr(BatchWorker, "_storm_solve", boom)
    jobs = family_jobs(10, fam="failfam")
    server = run_storm_server(jobs)
    try:
        worker = server.workers[0]
        assert worker.storm_evals == 10
        assert worker.storm_solves == 0
        assert worker.storm_fallbacks >= 10
        for job in jobs:
            assert len(placements(server, job.id)) == 1
        assert_zero_lost(server, jobs)
    finally:
        server.stop()
