"""Docker driver against a MOCK dockerd (reference model:
drivers/docker/driver_test.go runs against a real daemon; here a
unix-socket HTTP server speaks just enough Engine API — create/start/
wait/stop/exec/logs/stats/inspect — to drive the full lifecycle,
including the docklog companion streaming demuxed frames into the
logmon rotators)."""
from __future__ import annotations

import json
import re
import socket
import socketserver
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler

import pytest

from nomad_tpu.client.drivers.base import TaskConfig
from nomad_tpu.client.drivers.docker import (
    DockerDriver,
    _split_frames,
)


class _MockDockerd(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


def _frame(stream: int, payload: bytes) -> bytes:
    return bytes([stream, 0, 0, 0]) + struct.pack(
        ">I", len(payload)
    ) + payload


class _State:
    def __init__(self):
        self.containers = {}  # cid -> dict(state)
        self.execs = {}
        self.events = []
        self.lock = threading.Lock()
        self.seq = 0


def _make_handler(state: _State):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _json(self, obj, code=200):
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _body(self):
            n = int(self.headers.get("Content-Length") or 0)
            return json.loads(self.rfile.read(n) or b"{}")

        def do_GET(self):
            path = self.path.split("?")[0]
            if path.endswith("/version"):
                return self._json({"Version": "99.mock"})
            m = re.search(r"/containers/([^/]+)/json$", path)
            if m:
                c = state.containers.get(m.group(1))
                if c is None:
                    return self._json(
                        {"message": "no such container"}, 404
                    )
                return self._json(
                    {"State": {"Running": c["running"]}}
                )
            m = re.search(r"/containers/([^/]+)/stats$", path)
            if m:
                return self._json(
                    {
                        "cpu_stats": {"cpu_usage": {"total_usage": 12345}},
                        "memory_stats": {"usage": 1024 * 1024},
                    }
                )
            m = re.search(r"/containers/([^/]+)/logs$", path)
            if m:
                cid = m.group(1)
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "application/vnd.docker.raw-stream",
                )
                self.end_headers()
                c = state.containers.get(cid)
                sent = 0
                while c and c["running"]:
                    lines = c["log_lines"]
                    while sent < len(lines):
                        stream, data = lines[sent]
                        self.wfile.write(_frame(stream, data))
                        self.wfile.flush()
                        sent += 1
                    time.sleep(0.02)
                return
            m = re.search(r"/exec/([^/]+)/json$", path)
            if m:
                e = state.execs.get(m.group(1), {})
                return self._json(
                    {"ExitCode": e.get("exit_code", 0)}
                )
            if path.endswith("/events"):
                self.send_response(200)
                body = b"".join(
                    json.dumps(e).encode() + b"\n"
                    for e in state.events
                )
                self.send_header(
                    "Content-Length", str(len(body))
                )
                self.end_headers()
                self.wfile.write(body)
                return
            return self._json({"message": "not found"}, 404)

        def do_POST(self):
            path = self.path.split("?")[0]
            if path.endswith("/containers/create"):
                spec = self._body()
                if spec.get("Image") == "missing:latest":
                    return self._json(
                        {"message": "No such image"}, 404
                    )
                with state.lock:
                    state.seq += 1
                    cid = f"cid{state.seq}"
                state.containers[cid] = {
                    "spec": spec,
                    "running": False,
                    "exit_code": 0,
                    "log_lines": [],
                    "exited": threading.Event(),
                }
                state.events.append(
                    {"Type": "container", "Action": "create",
                     "id": cid}
                )
                return self._json({"Id": cid}, 201)
            m = re.search(r"/containers/([^/]+)/start$", path)
            if m:
                c = state.containers[m.group(1)]
                c["running"] = True
                # the "container" emits some output
                c["log_lines"].append((1, b"hello stdout\n"))
                c["log_lines"].append((2, b"hello stderr\n"))
                return self._json(None, 204)
            m = re.search(r"/containers/([^/]+)/wait$", path)
            if m:
                c = state.containers[m.group(1)]
                c["exited"].wait(timeout=60)
                return self._json(
                    {"StatusCode": c["exit_code"]}
                )
            m = re.search(r"/containers/([^/]+)/stop$", path)
            if m:
                c = state.containers[m.group(1)]
                c["exit_code"] = 0
                c["running"] = False
                c["exited"].set()
                return self._json(None, 204)
            m = re.search(r"/containers/([^/]+)/kill$", path)
            if m:
                c = state.containers[m.group(1)]
                c["exit_code"] = 137
                c["running"] = False
                c["exited"].set()
                return self._json(None, 204)
            m = re.search(r"/containers/([^/]+)/exec$", path)
            if m:
                body = self._body()
                with state.lock:
                    state.seq += 1
                    eid = f"eid{state.seq}"
                state.execs[eid] = {
                    "cmd": body.get("Cmd") or [],
                    "exit_code": 0,
                }
                return self._json({"Id": eid}, 201)
            m = re.search(r"/exec/([^/]+)/start$", path)
            if m:
                e = state.execs[m.group(1)]
                out = (
                    "ran: " + " ".join(e["cmd"])
                ).encode()
                body = _frame(1, out)
                self.send_response(200)
                self.send_header(
                    "Content-Length", str(len(body))
                )
                self.end_headers()
                self.wfile.write(body)
                return
            if path.endswith("/images/create"):
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")
                return
            return self._json({"message": "not found"}, 404)

        def do_DELETE(self):
            m = re.search(r"/containers/([^/]+)$", self.path.split("?")[0])
            if m and m.group(1) in state.containers:
                c = state.containers.pop(m.group(1))
                c["running"] = False
                c["exited"].set()
                return self._json(None, 204)
            return self._json({"message": "not found"}, 404)

    return Handler


@pytest.fixture
def mockerd(tmp_path):
    state = _State()
    sock = str(tmp_path / "docker.sock")
    srv = _MockDockerd(sock, _make_handler(state))
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield sock, state
    srv.shutdown()
    srv.server_close()


def test_attach_stream_demux():
    buf = _frame(1, b"abc") + _frame(2, b"de") + b"\x01\x00\x00"
    frames, rest = _split_frames(buf)
    assert frames == [(1, b"abc"), (2, b"de")]
    assert rest == b"\x01\x00\x00"


def test_docker_lifecycle_via_daemon_api(mockerd, tmp_path):
    sock, state = mockerd
    d = DockerDriver(sock_path=sock)
    assert d.fingerprint()["driver.docker"] == "1"
    assert d._server_version == "99.mock"

    logs_dir = str(tmp_path / "logs")
    cfg = TaskConfig(
        id="task1",
        name="web",
        alloc_id="alloc1",
        env={"FOO": "bar"},
        alloc_dir=str(tmp_path / "alloc"),
        logs_dir=logs_dir,
        config={
            "image": "redis:6",
            "command": "redis-server",
            "port_map": {"6380": 16380},
        },
    )
    handle = d.start_task(cfg)
    cid = handle.container
    assert state.containers[cid]["running"]
    spec = state.containers[cid]["spec"]
    assert spec["Image"] == "redis:6"
    assert "FOO=bar" in spec["Env"]

    # docklog companion streamed the demuxed frames into the logmon
    # rotators (the same files `alloc logs` reads)
    import os

    def rotated(kind):
        out = b""
        for name in sorted(os.listdir(logs_dir)):
            if name.startswith(f"web.{kind}."):
                with open(os.path.join(logs_dir, name), "rb") as f:
                    out += f.read()
        return out

    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if (
            b"hello stdout" in rotated("stdout")
            and b"hello stderr" in rotated("stderr")
        ):
            break
        time.sleep(0.05)
    assert b"hello stdout" in rotated("stdout")
    assert b"hello stderr" in rotated("stderr")

    # exec through /containers/<id>/exec + /exec/<id>/start
    rc, out = d.exec_task("task1", ["echo", "hi"])
    assert rc == 0 and out == b"ran: echo hi"

    # one-shot stats from the daemon
    stats = d.task_stats("task1")
    assert stats["memory_stats"]["usage"] == 1024 * 1024

    # events observability
    evs = d.api.events(0, int(time.time()) + 10)
    assert any(e.get("Action") == "create" for e in evs)

    # stop -> wait returns the daemon's exit code and the handle
    # settles
    d.stop_task("task1", timeout=2)
    res = d.wait_task("task1", timeout=5)
    assert res is not None and res.exit_code == 0
    d.destroy_task("task1", force=True)
    assert "task1" not in d.handles


def test_docker_pull_on_missing_image(mockerd, tmp_path):
    sock, state = mockerd
    d = DockerDriver(sock_path=sock)
    cfg = TaskConfig(
        id="task2",
        name="puller",
        alloc_dir=str(tmp_path / "a2"),
        config={"image": "missing:latest"},
    )
    # create 404s -> pull_image -> retry create (which 404s again in
    # the mock: assert the pull happened by the error shape)
    with pytest.raises(Exception):
        d.start_task(cfg)


def test_docker_recover_task(mockerd):
    sock, state = mockerd
    d = DockerDriver(sock_path=sock)
    handle = d.start_task(
        TaskConfig(id="task3", name="r", config={"image": "x:1"})
    )
    cid = handle.container
    # a fresh driver (client restart) recovers the running container
    d2 = DockerDriver(sock_path=sock)
    assert d2.recover_task("task3", {"container": cid})
    state.containers[cid]["exit_code"] = 7
    state.containers[cid]["running"] = False
    state.containers[cid]["exited"].set()
    res = d2.wait_task("task3", timeout=5)
    assert res is not None and res.exit_code == 7


def test_docker_restart_reuses_name_and_removes_exited(mockerd, tmp_path):
    """Task restart loop: the exited container is removed after wait
    (the CLI path's --rm equivalent) and a name conflict from a
    lingering container is cleared with a 409-retry — restarts must
    not fail with 'Driver Failure' (review r5)."""
    sock, state = mockerd
    d = DockerDriver(sock_path=sock)
    cfg = TaskConfig(
        id="taskR", name="r",
        alloc_dir=str(tmp_path / "aR"),
        config={"image": "x:1"},
    )
    h1 = d.start_task(cfg)
    cid1 = h1.container
    d.stop_task("taskR", timeout=1)
    assert d.wait_task("taskR", timeout=5).exit_code == 0
    # the waiter removed the exited container
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and cid1 in state.containers:
        time.sleep(0.05)
    assert cid1 not in state.containers
    # restart under the same task id succeeds
    h2 = d.start_task(cfg)
    assert h2.container != cid1
    assert state.containers[h2.container]["running"]


def test_docker_recover_reattaches_docklog(mockerd, tmp_path):
    """Client restart: recover_task must reattach the docklog
    companion, not just the wait loop (review r5 — logs silently
    stopped flowing after recovery)."""
    import os

    sock, state = mockerd
    logs_dir = str(tmp_path / "logsR")
    d = DockerDriver(sock_path=sock)
    h = d.start_task(
        TaskConfig(
            id="taskL", name="webL", logs_dir=logs_dir,
            config={"image": "x:1"},
        )
    )
    cid = h.container
    snap = d.handle_state("taskL")
    assert snap["container"] == cid
    assert snap["logs_dir"] == logs_dir

    d2 = DockerDriver(sock_path=sock)
    assert d2.recover_task("taskL", snap)
    # new output lands AFTER recovery; the reattached companion must
    # stream it into the rotators
    state.containers[cid]["log_lines"].append(
        (1, b"post-recovery line\n")
    )

    def rotated():
        out = b""
        if os.path.isdir(logs_dir):
            for name in sorted(os.listdir(logs_dir)):
                if name.startswith("webL.stdout."):
                    with open(
                        os.path.join(logs_dir, name), "rb"
                    ) as f:
                        out += f.read()
        return out

    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if b"post-recovery line" in rotated():
            break
        time.sleep(0.05)
    assert b"post-recovery line" in rotated()
