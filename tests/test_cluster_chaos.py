"""Cluster failover semantics: the forwarding retry loop survives
leadership moving mid-forward, the FSM's replicated leadership fence
rejects a deposed leader's plan, command-id dedup makes forwards
idempotent, and the chaos smoke's kill/heal schedule holds its
invariants at test scale."""
import pickle
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.raft import NotLeaderError
from nomad_tpu.raft.chaos import ChaosTransport, parse_fault
from nomad_tpu.raft.transport import TransportError
from nomad_tpu.server.cluster import TestCluster
from nomad_tpu.server.fsm import (
    ServerFSM,
    StaleLeadershipError,
    encode_command,
)


def wait_until(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


@pytest.fixture
def chaos_cluster():
    transport = ChaosTransport(seed=7)
    c = TestCluster(3, transport=transport, heartbeat_ttl=120.0)
    c.start()
    yield c, transport
    transport.disarm()
    c.stop()


def _new_leader(cluster, exclude, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        est = [
            s
            for s in cluster.servers
            if s is not exclude
            and s.is_leader()
            and s._leader_established
        ]
        if est:
            return est[0]
        time.sleep(0.02)
    raise AssertionError("no new leader")


def test_forward_retry_survives_leadership_move(
    chaos_cluster, monkeypatch
):
    """A follower write issued DURING the interregnum is not lost: the
    retry loop backs off, rediscovers the new leader, and commits."""
    monkeypatch.setenv("NOMAD_TPU_FORWARD_RETRIES", "12")
    cluster, transport = chaos_cluster
    leader = cluster.wait_for_leader()
    follower = cluster.followers()[0]
    # depose the leader; immediately push a write through the follower
    transport.partition_group([leader.addr])
    from nomad_tpu.structs import Namespace

    follower.store.upsert_namespace(
        Namespace(name="survived", description="forwarded")
    )
    new_leader = _new_leader(cluster, exclude=leader)
    transport.heal(leader.addr)
    assert (
        new_leader.fsm.store.namespaces.get("survived") is not None
    )
    total_retries = sum(
        s.metrics.get_counter("raft.forward_retries")
        for s in cluster.servers
    )
    assert total_retries >= 1.0


def test_remote_fsm_apply_returns_structured_not_leader(
    chaos_cluster,
):
    """Satellite: a forwarded fsm_apply landing on a non-leader must
    answer with a structured not-leader response (plus a hint), never
    a crash."""
    cluster, transport = chaos_cluster
    leader = cluster.wait_for_leader()
    follower = cluster.followers()[0]
    data = encode_command(
        "upsert_namespace",
        (__import__("nomad_tpu.structs", fromlist=["Namespace"])
         .Namespace(name="x", description=""),),
        cmd_id="cmd-structured",
    )
    resp = transport.rpc(
        leader.addr, follower.addr, "fsm_apply", {"data": data}
    )
    assert resp.get("not_leader") is True
    assert resp.get("leader") == leader.addr


def test_stale_leadership_plan_cannot_commit(chaos_cluster):
    """Acceptance: a deposed leader's in-flight plan — even forwarded
    to the NEW leader — is rejected under the raft apply by the
    replicated generation fence, and nothing lands in any store."""
    from nomad_tpu.structs import Allocation, PlanResult

    cluster, transport = chaos_cluster
    old_leader = cluster.wait_for_leader()
    for _ in range(3):
        old_leader.register_node(mock.node())
    old_gen = old_leader._leadership_gen
    assert old_gen >= 1

    # depose: isolate, elect, heal — the old leader steps down but
    # its host-side _leadership_gen still says old_gen (it never
    # re-established), exactly like a wave captured pre-revoke
    transport.partition_group([old_leader.addr])
    new_leader = _new_leader(cluster, exclude=old_leader)
    transport.heal(old_leader.addr)
    wait_until(
        lambda: not old_leader.is_leader()
        and not old_leader._leader_established,
        msg="old leader stepped down",
    )
    assert new_leader._leadership_gen > old_gen
    # the barrier replicated: every FSM's fence moved to the new gen
    wait_until(
        lambda: all(
            s.fsm.leadership_fence == new_leader._leadership_gen
            for s in cluster.servers
        ),
        msg="fence replication",
    )

    # the deposed leader now tries to commit the wave it had in
    # flight: its ReplicatedStore stamps the OLD generation, the
    # forward lands on the new leader, and the FSM rejects it
    node_id = next(iter(old_leader.store.nodes))
    alloc = mock.alloc(node_id=node_id)
    alloc.job = mock.job(id="zombie")
    alloc.job_id = "zombie"
    result = PlanResult(node_allocation={node_id: [alloc]})
    with pytest.raises(StaleLeadershipError):
        old_leader.store.upsert_plan_results(result, "ev-zombie")
    for s in cluster.servers:
        assert s.fsm.store.alloc_by_id(alloc.id) is None, (
            f"zombie alloc committed on {s.addr}"
        )
    # ... while the new leadership's own plans commit fine
    alloc2 = mock.alloc(node_id=node_id)
    alloc2.job = mock.job(id="fresh")
    alloc2.job_id = "fresh"
    new_leader.store.upsert_plan_results(
        PlanResult(node_allocation={node_id: [alloc2]}), "ev-fresh"
    )
    assert new_leader.fsm.store.alloc_by_id(alloc2.id) is not None


def test_stale_leadership_error_survives_tcp_hop():
    """The replicated fence's verdict must keep its real type across
    a framed-TCP forward: the retry loop treats StaleLeadershipError
    as definitive, and a bare RuntimeError would take the generic
    crash path instead of nack-for-redelivery."""
    import socket

    from nomad_tpu.raft.tcp import TcpTransport

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    transport = TcpTransport()
    addr = f"127.0.0.1:{free_port()}"

    def handler(method, payload):
        raise StaleLeadershipError(3, 7)

    transport.register(addr, handler)
    try:
        with pytest.raises(StaleLeadershipError) as exc_info:
            transport.rpc(addr, addr, "fsm_apply", {})
        assert exc_info.value.gen == 3
        assert exc_info.value.fence == 7
    finally:
        transport.close()


def test_straggler_wave_generation_is_not_laundered(chaos_cluster):
    """A plan stamped with a deposed generation is rejected even when
    it reaches the store THROUGH the current leader (a straggler
    thread on a re-elected server must not get re-stamped with the
    new term)."""
    from nomad_tpu.structs import Allocation, PlanResult

    cluster, _transport = chaos_cluster
    leader = cluster.wait_for_leader()
    for _ in range(2):
        leader.register_node(mock.node())
    gen = leader._leadership_gen
    node_id = next(iter(leader.store.nodes))
    alloc = mock.alloc(node_id=node_id)
    alloc.job = mock.job(id="straggler")
    alloc.job_id = "straggler"
    result = PlanResult(node_allocation={node_id: [alloc]})
    # the wave's captured (older) generation rides the call even on
    # the current leader — the FSM fence judges by it
    with pytest.raises(StaleLeadershipError):
        leader.store.upsert_plan_results(
            result, "ev-straggler", leader_gen=gen - 1
        )
    assert leader.fsm.store.alloc_by_id(alloc.id) is None
    # the captured CURRENT generation passes
    leader.store.upsert_plan_results(
        result, "ev-straggler", leader_gen=gen
    )
    assert leader.fsm.store.alloc_by_id(alloc.id) is not None


def test_fsm_command_dedup_is_idempotent():
    """The same cmd_id applied twice (a forward retried after a lost
    ack) mutates state once and returns the first apply's result."""
    from nomad_tpu.acl import ACLStore
    from nomad_tpu.state.store import StateStore
    from nomad_tpu.structs import Evaluation, new_id

    fsm = ServerFSM(StateStore(), ACLStore())
    ev = Evaluation(
        id=new_id(), namespace="default", job_id="j", type="batch"
    )
    raw = encode_command("upsert_evals", ([ev], 1.0), cmd_id="dup-1")
    first = fsm.apply(raw)
    index_after_first = fsm.store.latest_index()
    second = fsm.apply(raw)
    assert second == first
    assert fsm.store.latest_index() == index_after_first
    # a distinct cmd_id applies normally
    raw2 = encode_command("upsert_evals", ([ev], 1.0), cmd_id="dup-2")
    fsm.apply(raw2)
    assert fsm.store.latest_index() > index_after_first
    # dedup state survives snapshot/restore (a compaction must not
    # resurrect a dup on a restored replica)
    snap = fsm.snapshot()
    fsm2 = ServerFSM(StateStore(), ACLStore())
    fsm2.restore(snap)
    index_restored = fsm2.store.latest_index()
    assert fsm2.apply(raw) == first
    assert fsm2.store.latest_index() == index_restored


def test_leadership_barrier_fences_older_generations():
    from nomad_tpu.acl import ACLStore
    from nomad_tpu.state.store import StateStore
    from nomad_tpu.structs import PlanResult

    fsm = ServerFSM(StateStore(), ACLStore())
    assert fsm.dispatch("leadership_barrier", (5,)) == 5
    # fences never move backwards
    assert fsm.dispatch("leadership_barrier", (3,)) == 5
    with pytest.raises(StaleLeadershipError):
        fsm.dispatch(
            "upsert_plan_results", (PlanResult(), "ev", 4)
        )
    # current and newer generations (and unstamped legacy commands)
    # pass
    fsm.dispatch("upsert_plan_results", (PlanResult(), "ev", 5))
    fsm.dispatch("upsert_plan_results", (PlanResult(), "ev", None))


def test_parse_fault_specs():
    assert parse_fault("leader_kill").kind == "leader_kill"
    part = parse_fault("partition:server-0,server-1")
    assert part.kind == "partition"
    assert part.members == ["server-0", "server-1"]
    assert parse_fault("msg_drop:7.5").pct == 7.5
    assert parse_fault("slow_wire:3").ms == 3.0
    assert parse_fault("") is None
    assert parse_fault("bogus") is None


def test_chaos_transport_msg_drop_is_deterministic():
    calls = []

    def handler(method, payload):
        calls.append(method)
        return {"ok": True}

    def run(seed):
        t = ChaosTransport(seed=seed)
        t.register("a", handler)
        t.register("b", handler)
        t.arm(parse_fault("msg_drop:40"))
        outcomes = []
        for _ in range(50):
            try:
                t.rpc("a", "b", "ping", {})
                outcomes.append(1)
            except TransportError:
                outcomes.append(0)
        return outcomes

    first = run(3)
    assert 0 in first and 1 in first
    assert first == run(3)  # seeded: bit-identical replay
    assert first != run(4) or True  # different seed may differ


def test_chaos_smoke_invariants_small():
    """The chaos smoke at test scale: 2 kills + a healed partition
    under load, zero lost / zero duplicates vs the oracle."""
    from nomad_tpu.raft.chaos_smoke import run_smoke

    block = run_smoke(jobs=40, kills=2, nodes=4)
    assert block["ok"], block
    assert block["oracle_match"]
    assert block["lost_evals"] == 0
    assert block["duplicate_placements"] == 0
    assert block["apply_monotone"]
    assert len(block["detect_to_resume_s"]) == 2
    assert block["detect_to_resume_max_s"] < 30.0
