"""External driver plugin processes over the wire protocol (reference
plugins/base/plugin.go go-plugin handshake + plugins/drivers gRPC
surface; here: subprocess + unix socket + framed msgpack wire).
"""
import sys
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client
from nomad_tpu.client.drivers.external import ExternalDriver
from nomad_tpu.server import Server
from nomad_tpu.structs import Node, Task


def wait_until(cond, timeout=10.0, interval=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def plugin():
    d = ExternalDriver(
        [sys.executable, "-m", "nomad_tpu.client.drivers.external",
         "mock_driver"],
        name="mock_driver",
    )
    yield d
    d.shutdown()


def test_plugin_handshake_and_fingerprint(plugin):
    fp = plugin.fingerprint()
    assert fp.get("driver.mock_driver") == "1"


def test_plugin_task_lifecycle(plugin):
    from nomad_tpu.client.drivers.base import TaskConfig

    plugin.start_task(
        TaskConfig(id="t1", config={"run_for": 0.05, "exit_code": 2})
    )
    res = plugin.wait_task("t1", timeout=5)
    assert res is not None and res.exit_code == 2

    code, out = plugin.exec_task("t1", ["echo", "hi"])
    assert code == 0
    assert b"mock exec" in out


def test_plugin_start_error_propagates(plugin):
    from nomad_tpu.client.drivers.base import TaskConfig

    with pytest.raises(RuntimeError):
        plugin.start_task(
            TaskConfig(id="t2", config={"start_error": "boom"})
        )


def test_plugin_recoverable_error(plugin):
    from nomad_tpu.client.drivers.base import (
        RecoverableError,
        TaskConfig,
    )

    with pytest.raises(RecoverableError):
        plugin.start_task(
            TaskConfig(
                id="t3",
                config={
                    "start_error": "flaky",
                    "start_error_recoverable": True,
                },
            )
        )


def test_end_to_end_placement_on_external_driver(tmp_path):
    """A job scheduled onto a client whose driver runs out-of-process."""
    srv = Server(heartbeat_ttl=60.0)
    srv.start()
    ext = ExternalDriver(
        [sys.executable, "-m", "nomad_tpu.client.drivers.external",
         "raw_exec"],
        name="raw_exec",
    )
    cli = Client(
        srv,
        node=Node(),
        data_dir=str(tmp_path),
        heartbeat_interval=5.0,
        drivers={"raw_exec": ext},
    )
    cli.start()
    try:
        job = mock.job(id="extjob")
        job.type = "batch"
        tg = job.task_groups[0]
        tg.count = 1
        tg.tasks[0] = Task(
            name="say",
            driver="raw_exec",
            config={
                "command": "/bin/sh",
                "args": ["-c", "echo from-plugin-process"],
            },
        )
        srv.register_job(job)
        assert wait_until(
            lambda: any(
                a.client_status == "complete"
                for a in srv.store.allocs_by_job("default", "extjob")
            )
        ), "alloc did not complete via external driver"
        alloc = srv.store.allocs_by_job("default", "extjob")[0]
        out = srv.read_task_log(alloc.id, "say", "stdout")
        assert b"from-plugin-process" in out
    finally:
        cli.stop()
        srv.stop()
        ext.shutdown()
