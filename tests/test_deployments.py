"""Deployment rolling-update and node-drain integration tests
(reference model: nomad/deploymentwatcher/deployments_watcher_test.go,
nomad/drainer_int_test.go).
"""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server
from nomad_tpu.structs import (
    DrainStrategy,
    MigrateStrategy,
    Task,
    UpdateStrategy,
)


def wait_until(cond, timeout=10.0, interval=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def server():
    s = Server(num_schedulers=2, heartbeat_ttl=60.0, seed=11)
    # fast health checks for tests
    s.deployment_watcher.interval = 0.05
    s.drainer.interval = 0.05
    s.start()
    yield s
    s.stop()


def _deployed_job(count=4, canary=0, max_parallel=2, auto_revert=False,
                  auto_promote=False):
    job = mock.job()
    job.task_groups[0].count = count
    job.task_groups[0].update = UpdateStrategy(
        max_parallel=max_parallel,
        min_healthy_time_s=0.05,
        healthy_deadline_s=5.0,
        progress_deadline_s=30.0,
        canary=canary,
        auto_revert=auto_revert,
        auto_promote=auto_promote,
    )
    return job


def _mark_running(server, job):
    """Simulate clients reporting the allocs running."""
    allocs = [
        a
        for a in server.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status() and a.client_status == "pending"
    ]
    for a in allocs:
        a.client_status = "running"
    if allocs:
        server.store.upsert_allocs(allocs)
    return allocs


def test_deployment_created_and_completes(server):
    for _ in range(4):
        server.register_node(mock.node())
    job = _deployed_job()
    server.register_job(job)
    assert server.drain_to_idle(10)

    # v0 of a job: no running allocs before, so a deployment is created
    d = server.store.latest_deployment_by_job(job.namespace, job.id)
    assert d is not None
    assert d.task_groups["web"].desired_total == 4

    assert wait_until(
        lambda: bool(_mark_running(server, job)) or True, timeout=1
    )
    _mark_running(server, job)
    assert wait_until(
        lambda: server.store.latest_deployment_by_job(
            job.namespace, job.id
        ).status
        == "successful",
        timeout=15,
    )
    assert server.store.job_by_id(job.namespace, job.id).stable


def test_rolling_update_respects_max_parallel(server):
    for _ in range(6):
        server.register_node(mock.node())
    job = _deployed_job(count=4, max_parallel=1)
    server.register_job(job)
    assert server.drain_to_idle(10)
    _mark_running(server, job)
    assert wait_until(
        lambda: server.store.latest_deployment_by_job(
            job.namespace, job.id
        ).status
        == "successful",
        timeout=15,
    )

    # register v1 with a changed task config -> destructive update
    job2 = _deployed_job(count=4, max_parallel=1)
    job2.id = job.id
    job2.task_groups[0].tasks[0].config = {"command": "/bin/sleep"}
    server.register_job(job2)
    assert server.drain_to_idle(10)

    # only max_parallel=1 alloc may be destroyed before replacements
    # become healthy
    stopped = [
        a
        for a in server.store.allocs_by_job(job.namespace, job.id)
        if a.desired_status == "stop"
    ]
    assert len(stopped) == 1

    # drive the rolling update to completion by marking each new batch
    # running
    def pump():
        _mark_running(server, job)
        d = server.store.latest_deployment_by_job(job.namespace, job.id)
        return d.job_version == 1 and d.status == "successful"

    assert wait_until(pump, timeout=20)
    live = [
        a
        for a in server.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status()
    ]
    assert len(live) == 4
    assert all(a.job.version == 1 for a in live if a.job)


def test_canary_deployment_requires_promotion(server):
    for _ in range(6):
        server.register_node(mock.node())
    job = _deployed_job(count=3, canary=1, max_parallel=1)
    server.register_job(job)
    assert server.drain_to_idle(10)
    _mark_running(server, job)
    assert wait_until(
        lambda: server.store.latest_deployment_by_job(
            job.namespace, job.id
        ).status
        == "successful",
        timeout=15,
    )

    job2 = _deployed_job(count=3, canary=1, max_parallel=1)
    job2.id = job.id
    job2.task_groups[0].tasks[0].config = {"command": "/bin/true"}
    server.register_job(job2)
    assert server.drain_to_idle(10)

    d = server.store.latest_deployment_by_job(job.namespace, job.id)
    assert d.job_version == 1
    state = d.task_groups["web"]
    assert state.desired_canaries == 1
    # v0 allocs still running while the canary is unpromoted
    v0_live = [
        a
        for a in server.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status() and a.job and a.job.version == 0
    ]
    assert len(v0_live) == 3

    _mark_running(server, job)
    time.sleep(0.3)
    _mark_running(server, job)
    # promote and drive to completion
    assert wait_until(
        lambda: d.task_groups["web"].healthy_allocs >= 1, timeout=10
    )
    server.deployment_watcher.promote(d.id)

    def pump():
        _mark_running(server, job)
        dd = server.store.latest_deployment_by_job(job.namespace, job.id)
        return dd.status == "successful" and dd.job_version == 1

    assert wait_until(pump, timeout=20)


def test_failed_deployment_auto_reverts(server):
    for _ in range(6):
        server.register_node(mock.node())
    job = _deployed_job(count=2, max_parallel=2, auto_revert=True)
    server.register_job(job)
    assert server.drain_to_idle(10)
    _mark_running(server, job)
    assert wait_until(
        lambda: server.store.latest_deployment_by_job(
            job.namespace, job.id
        ).status
        == "successful",
        timeout=15,
    )

    job2 = _deployed_job(count=2, max_parallel=2, auto_revert=True)
    job2.id = job.id
    job2.task_groups[0].tasks[0].config = {"command": "/bin/false"}
    server.register_job(job2)
    assert server.drain_to_idle(10)

    # the v1 allocs fail health
    v1 = [
        a
        for a in server.store.allocs_by_job(job.namespace, job.id)
        if a.job and a.job.version == 1 and not a.terminal_status()
    ]
    assert v1
    for a in v1:
        a.client_status = "failed"
    server.store.upsert_allocs(v1)

    assert wait_until(
        lambda: any(
            d.status == "failed"
            for d in server.store.deployments_by_job(
                job.namespace, job.id
            )
        ),
        timeout=10,
    )
    # auto-revert re-registered the stable version as v2
    assert wait_until(
        lambda: server.store.job_by_id(job.namespace, job.id).version
        >= 2,
        timeout=10,
    )
    reverted = server.store.job_by_id(job.namespace, job.id)
    assert reverted.task_groups[0].tasks[0].config == {
        "command": "/bin/date"
    }


def test_node_drain_migrates_allocs(server):
    nodes = [mock.node() for _ in range(4)]
    for n in nodes:
        server.register_node(n)
    job = mock.job()
    job.task_groups[0].count = 4
    job.task_groups[0].migrate = MigrateStrategy(max_parallel=2)
    server.register_job(job)
    assert server.drain_to_idle(10)
    _mark_running(server, job)

    victim = server.store.allocs_by_job(job.namespace, job.id)[0].node_id
    server.update_node_drain(
        victim, True, DrainStrategy(force_deadline_unix=time.time() + 30)
    )

    assert wait_until(
        lambda: not [
            a
            for a in server.store.allocs_by_node(victim)
            if not a.terminal_status()
        ],
        timeout=15,
    )
    # node finished draining: flag cleared, stays ineligible
    assert wait_until(
        lambda: not server.store.node_by_id(victim).drain, timeout=10
    )
    assert (
        server.store.node_by_id(victim).scheduling_eligibility
        == "ineligible"
    )
    live = [
        a
        for a in server.store.allocs_by_job(job.namespace, job.id)
        if not a.terminal_status()
    ]
    assert len(live) == 4
    assert all(a.node_id != victim for a in live)
