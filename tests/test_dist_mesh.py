"""Multi-host mesh tests (ROADMAP item 3): the sharded storm auction
must be bit-identical to the single-device solve, the per-host flush
primitive must be bit-identical to the replicated PR 8 staging, the
single-process distributed path must be bit-identical to the PR 8
sharded path (the degenerate-parity floor), and a REAL 2-process
jax.distributed world (spawned CPU workers, gloo collectives) must
run the full assemble/launch/fetch/replay chain with zero lost evals
and cross-host parity.
"""
import copy
import random

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server
from nomad_tpu.structs import compute_node_class


def _mesh8():
    from nomad_tpu.parallel.mesh import make_mesh

    return make_mesh(8, eval_axis=1)


# ---------------------------------------------------------------------------
# sharded storm auction == single-device solve, bit for bit
# ---------------------------------------------------------------------------


def _storm_problem(E, A, C, ask=(100.0, 100.0, 100.0), limit=2,
                   seed=0, shared_perm=False, feas_p=0.15):
    from nomad_tpu.ops.solve import StormInputs

    rng = np.random.default_rng(seed)
    if shared_perm:
        perm = np.tile(
            rng.permutation(C).astype(np.int32), (E, 1)
        )
    else:
        perm = np.stack(
            [rng.permutation(C).astype(np.int32) for _ in range(E)]
        )
    inp = StormInputs(
        feasible=rng.random((E, C)) > feas_p,
        affinity=np.where(
            rng.random((E, C)) > 0.8, rng.random((E, C)), 0.0
        ),
        collisions=(rng.random((E, C)) > 0.9).astype(np.int32),
        perm=perm,
        limit=np.full(E, limit, np.int32),
        n_cand=np.full(E, C, np.int32),
        eval_of=(np.arange(A) % E).astype(np.int32),
        penalty=rng.random((A, C)) > 0.95,
        ask=np.tile(np.asarray(ask, np.float64), (A, 1)),
        desired=np.ones(A, np.int32),
        real=np.ones(A, bool),
        pre_cpu=np.zeros(C),
        pre_mem=np.zeros(C),
        pre_disk=np.zeros(C),
    )
    cols = tuple(
        np.asarray(x, np.float64)
        for x in (
            np.full(C, 4000.0),
            np.full(C, 8192.0),
            np.full(C, 100000.0),
            rng.integers(0, 2000, C).astype(np.float64),
            rng.integers(0, 4096, C).astype(np.float64),
            np.zeros(C),
        )
    )
    return inp, cols


def _run_both(inp, cols, max_rounds, mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nomad_tpu.ops.solve import (
        storm_assignment,
        storm_assignment_sharded,
    )
    from nomad_tpu.sched.storm import stage_for_mesh

    single = storm_assignment(
        inp, cols, spread_fit=False, max_rounds=max_rounds
    )
    sharded = storm_assignment_sharded(
        mesh, spread_fit=False, max_rounds=max_rounds
    )(
        stage_for_mesh(inp, mesh),
        tuple(
            jax.device_put(
                c, NamedSharding(mesh, P("nodes"))
            )
            for c in cols
        ),
    )
    return (
        tuple(np.asarray(x) for x in single),
        tuple(np.asarray(x) for x in sharded),
    )


NAMES = ("assigned", "pulls", "acc_round", "score", "greedy",
         "rounds")


@pytest.mark.parametrize(
    "E,A,C,kw",
    [
        # identical-ask dog-pile on one shared walk order: the
        # contention case the auction exists for
        (16, 64, 256, dict(ask=(1000.0, 100.0, 100.0),
                           shared_perm=True)),
        # mixed random feasibility / affinities / penalties
        (8, 32, 64, dict(seed=3)),
        (4, 8, 128, dict(seed=9, limit=5)),
        # degenerate one-row storm: the greedy-walk parity floor
        (1, 1, 16, dict(seed=7, limit=3)),
        # infeasible-heavy: NO_NODE rows must match too
        (16, 128, 64, dict(ask=(3000.0, 4000.0, 50000.0), seed=5)),
    ],
)
def test_sharded_storm_bit_identical_to_single_device(E, A, C, kw):
    """Every output of the node-sharded auction — assignments, pulls,
    acceptance rounds, scores, greedy picks AND the round count —
    must equal the single-device solve bit-for-bit, including
    NO_NODE rows."""
    inp, cols = _storm_problem(E, A, C, **kw)
    single, sharded = _run_both(inp, cols, A, _mesh8())
    for name, s, m in zip(NAMES, single, sharded):
        assert np.array_equal(s, m), (
            f"sharded storm diverged in {name}"
        )


def test_sharded_storm_padding_and_rounds():
    """Padding rows stay NO_NODE on the sharded path, and a
    round-capped solve caps identically."""
    inp, cols = _storm_problem(4, 16, 64, seed=2)
    real = np.ones(16, bool)
    real[11:] = False
    inp = inp._replace(real=real)
    single, sharded = _run_both(inp, cols, 2, _mesh8())
    for name, s, m in zip(NAMES, single, sharded):
        assert np.array_equal(s, m), name
    assert (single[0][11:] == -1).all()
    assert int(single[5]) <= 2


# ---------------------------------------------------------------------------
# per-host flush primitive == replicated PR 8 staging
# ---------------------------------------------------------------------------


def test_patch_rows_hostlocal_matches_replicated():
    """`patch_rows_hostlocal` (per-device shard-local staging — the
    multi-host protocol) must produce a mirror bit-identical to
    `patch_rows_sharded` (replicated staging — the PR 8 protocol)
    for the same dirty set."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nomad_tpu.ops.batch import (
        hostlocal_staging,
        patch_rows_hostlocal,
        patch_rows_sharded,
        pow2_bucket,
    )

    mesh = _mesh8()
    C = 64
    rng = np.random.default_rng(13)
    col_host = rng.random(C)
    sharding = NamedSharding(mesh, P("nodes"))

    for dirty in (
        [0],                       # one shard only
        [3, 8, 9, 17, 40, 63],     # several shards
        list(range(24, 48)),       # two full shards
        sorted(rng.choice(C, 20, replace=False).tolist()),
    ):
        idx = np.asarray(sorted(dirty), np.int32)
        vals_src = rng.random(C)

        # replicated PR 8 staging
        width = pow2_bucket(len(idx), floor=8)
        idx_p = np.full(width, C, np.int32)
        idx_p[: len(idx)] = idx
        vals_p = np.zeros(width)
        vals_p[: len(idx)] = vals_src[idx]
        a = patch_rows_sharded(mesh)(
            jax.device_put(col_host, sharding), idx_p, vals_p
        )

        # per-device shard-local staging
        idx_stack, per_dev, w = hostlocal_staging(mesh, idx, C)
        n_dev = mesh.devices.size
        vals_stack = np.zeros((n_dev, w))
        for d, sel in enumerate(per_dev):
            vals_stack[d, : len(sel)] = vals_src[sel]
        b = patch_rows_hostlocal(mesh)(
            jax.device_put(col_host, sharding),
            jax.device_put(idx_stack, sharding),
            jax.device_put(vals_stack, sharding),
        )
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=str(dirty)
        )
        # and both equal the host-side oracle
        want = col_host.copy()
        want[idx] = vals_src[idx]
        np.testing.assert_array_equal(np.asarray(b), want)


# ---------------------------------------------------------------------------
# single-process degenerate parity: DIST=1 == the PR 8 sharded path
# ---------------------------------------------------------------------------


def _make_nodes(n, seed=0):
    rng = random.Random(seed)
    nodes = []
    for i in range(n):
        node = mock.node(id=f"dp-node-{seed}-{i}")
        node.node_resources.cpu = rng.choice([4000, 8000])
        node.node_resources.memory_mb = rng.choice([8192, 16384])
        node.computed_class = compute_node_class(node)
        nodes.append(node)
    return nodes


def _make_jobs(n, seed=1):
    rng = random.Random(seed)
    jobs = []
    for i in range(n):
        job = mock.job(id=f"dp-{i}")
        job.task_groups[0].count = rng.randint(1, 4)
        job.task_groups[0].tasks[0].resources.cpu = rng.choice(
            [200, 400]
        )
        jobs.append(job)
    return jobs


def _placements(server, jobs):
    return sorted(
        (j.id, a.name, a.node_id)
        for j in jobs
        for a in server.store.allocs_by_job("default", j.id)
        if not a.terminal_status()
    )


def _outcomes(server, jobs):
    return sorted(
        (
            j.id,
            e.status,
            e.status_description,
            tuple(sorted(e.queued_allocations.items())),
        )
        for j in jobs
        for e in server.store.evals_by_job("default", j.id)
    )


def _metrics_view(server, jobs):
    """AllocMetrics from the explain ring, wall-clock fields
    stripped."""
    from nomad_tpu.explain import EXPLAIN

    out = []
    for j in jobs:
        for ev in sorted(
            server.store.evals_by_job("default", j.id),
            key=lambda e: e.create_index,
        ):
            rec = EXPLAIN.get(ev.id)
            if rec is None:
                out.append((j.id, None))
                continue
            tgs = {}
            for tg, entry in rec["TaskGroups"].items():
                metric = entry.get("Metric")
                if metric is not None:
                    metric = {
                        k: v
                        for k, v in metric.items()
                        if k != "AllocationTime"
                    }
                tgs[tg] = (
                    entry["Placed"], entry["Failed"],
                    entry["Winner"], metric,
                )
            out.append((j.id, tgs))
    return out


def _run_server(jobs, nodes):
    from nomad_tpu.ops.batch import pow2_bucket

    server = Server(
        num_schedulers=1, seed=47, batch_pipeline=True
    )
    for node in nodes:
        server.register_node(copy.deepcopy(node))
    server.start()
    try:
        worker = server.workers[0]
        assert worker._mesh is not None
        assert worker._mesh_hosts == 1
        for job in jobs:
            server.register_job(copy.deepcopy(job))
        assert server.drain_to_idle(60)
        table = server.store.node_table
        # warm sharded flush with a known dirty set: the byte
        # accounting must be the PR 8 replicated closed form
        gen = worker._usage_cache_sharded["gen"]
        _, dirty = server.store.usage_delta_since(gen)
        worker._device_columns(table, sharded=True)
        staged = server.metrics.get_gauge("mesh.bytes_per_flush")
        if dirty:
            width = pow2_bucket(len(dirty), floor=8)
            assert staged == 3 * (width * 4 + width * 8)
        else:
            assert staged == 0.0
        assert server.metrics.get_gauge("mesh.hosts") == 1.0
        return (
            _placements(server, jobs),
            _outcomes(server, jobs),
            _metrics_view(server, jobs),
            staged,
        )
    finally:
        server.stop()


def test_single_process_dist_path_bit_identical(monkeypatch):
    """With one process, the distributed mesh path (NOMAD_TPU_DIST=1)
    must be bit-identical to the PR 8 sharded path: placements,
    outcomes, AllocMetrics and mirror flush bytes."""
    monkeypatch.setenv("NOMAD_TPU_MESH", "1")
    # strict replay: relaxed mode's wave-snapshot score envelope is
    # documented run-to-run jitter — strict pins full score-metric
    # bit-identity (same contract the PR 8 parity suite uses)
    monkeypatch.setenv("NOMAD_TPU_REPLAY_STRICT", "1")
    jobs = _make_jobs(8, seed=3)
    nodes = _make_nodes(12, seed=5)

    monkeypatch.delenv("NOMAD_TPU_DIST", raising=False)
    base = _run_server(jobs, nodes)

    monkeypatch.setenv("NOMAD_TPU_DIST", "1")
    monkeypatch.setenv("NOMAD_TPU_DIST_PROCS", "1")
    monkeypatch.setenv("NOMAD_TPU_DIST_ID", "0")
    dist = _run_server(jobs, nodes)

    assert base[0] == dist[0], "placements diverged"
    assert base[1] == dist[1], "eval outcomes diverged"
    assert base[2] == dist[2], "AllocMetrics diverged"
    assert base[3] == dist[3], "mirror flush bytes diverged"
    assert base[0], "nothing placed"


def test_dist_config_misconfig_raises(monkeypatch):
    """An opted-in world with malformed knobs must RAISE, never
    silently degrade to single-host — the peers would deadlock in
    their first collective waiting for the missing member."""
    from nomad_tpu.parallel.mesh import dist_config

    monkeypatch.setenv("NOMAD_TPU_DIST", "1")
    monkeypatch.setenv("NOMAD_TPU_DIST_PROCS", "two")
    with pytest.raises(ValueError):
        dist_config()
    monkeypatch.setenv("NOMAD_TPU_DIST_PROCS", "2")
    monkeypatch.setenv("NOMAD_TPU_DIST_ID", "2")
    with pytest.raises(ValueError):
        dist_config()
    monkeypatch.setenv("NOMAD_TPU_DIST_ID", "1")
    cfg = dist_config()
    assert (cfg.num_processes, cfg.process_id) == (2, 1)
    # the documented off-switch: <=1 keeps distributed init off
    monkeypatch.setenv("NOMAD_TPU_DIST_PROCS", "0")
    assert dist_config().num_processes == 1
    monkeypatch.setenv("NOMAD_TPU_DIST", "0")
    assert dist_config() is None


# ---------------------------------------------------------------------------
# the real thing: a 2-process jax.distributed world
# ---------------------------------------------------------------------------


def test_two_process_distributed_smoke():
    """Spawn a REAL 2-process distributed world (CPU backend, gloo)
    and run the full assemble/launch/fetch/replay chain, the
    per-host cross-host flush, and the sharded storm solve through
    it — zero lost evals, closed-form per-host flush bytes, storm
    solve bit-identical to single-device, and placement digests
    identical across processes."""
    from nomad_tpu.parallel.dist_smoke import launch

    row = launch(procs=2, timeout=360.0)
    assert row["procs"] == 2
    assert row["global_devices"] == 4
    assert row["zero_lost"] is True
    assert row["cross_host_parity"] is True
    assert row["chain"]["mesh_launches"] >= 1
    assert row["chain"]["placements"] > 0
    assert row["storm"]["solves"] >= 1
    assert row["storm_kernel"]["bit_identical"] is True
    # the acceptance gauge: per-host cross-host traffic is O(dirty
    # rows), not O(nodes)
    flush = row["flush"]
    assert (
        flush["bytes_per_flush_delta_per_host"]
        < flush["bytes_per_flush_full_per_host"]
    )
