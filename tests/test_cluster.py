"""Replicated control-plane tests: an in-process 3-server raft cluster
scheduling real jobs (the shape of the reference's nomad.TestServer +
TestJoin integration tests, nomad/testing.go:44, leader_test.go)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server.cluster import TestCluster
from nomad_tpu.structs import SchedulerConfiguration


def wait_until(pred, timeout=8.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


@pytest.fixture
def cluster():
    c = TestCluster(3, heartbeat_ttl=60.0)
    c.start()
    yield c
    c.stop()


def register_capacity(server, n_nodes=3):
    nodes = [mock.node() for _ in range(n_nodes)]
    for node in nodes:
        server.register_node(node)
    return nodes


def test_job_schedules_and_replicates(cluster):
    leader = cluster.wait_for_leader()
    register_capacity(leader)
    job = mock.job(id="web")
    leader.register_job(job)
    assert leader.drain_to_idle(timeout=10.0)
    allocs = leader.store.allocs_by_job("default", "web")
    assert len(allocs) == job.task_groups[0].count
    # every follower's local store converges to the same allocations
    for f in cluster.followers():
        wait_until(
            lambda f=f: {
                a.id for a in f.fsm.store.allocs_by_job("default", "web")
            }
            == {a.id for a in allocs},
            msg=f"alloc replication to {f.addr}",
        )
        # and the same modify indexes (deterministic FSM application);
        # allow the in-flight tail of the log to land first
        wait_until(
            lambda f=f: f.fsm.store.latest_index()
            == leader.fsm.store.latest_index(),
            msg=f"index convergence on {f.addr}",
        )


def test_plan_normalization_roundtrip_and_size():
    """Stops/preemptions replicate as AllocationDiffs and reconstitute
    bit-identically against local state, at a fraction of the wire
    size (reference plan_apply.go:324-344 normalizePlan +
    AllocationDiff)."""
    from nomad_tpu.server.fsm import (
        denormalize_plan_result,
        encode_command,
        normalize_plan_result,
    )
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import Plan, PlanResult

    def build_store():
        store = StateStore()
        node = mock.node()
        node.id = "node-1"
        store.upsert_node(node)
        allocs = []
        for i in range(4):
            a = mock.alloc(node_id=node.id)
            a.id = f"alloc-{i}"
            a.job = mock.job(id="j")
            allocs.append(a)
        store.upsert_allocs(allocs)
        return store, node, allocs

    store, node, allocs = build_store()
    plan = Plan(eval_id="ev1")
    plan.append_stopped_alloc(allocs[0], "alloc not needed", "")
    plan.append_stopped_alloc(allocs[1], "node drained", "lost")
    plan.append_preempted_alloc(allocs[2], "winner-alloc")
    result = PlanResult(
        node_update=dict(plan.node_update),
        node_preemptions=dict(plan.node_preemptions),
    )

    norm = normalize_plan_result(result)
    assert norm.normalized
    full_size = len(encode_command("upsert_plan_results", (result, "ev1")))
    norm_size = len(encode_command("upsert_plan_results", (norm, "ev1")))
    assert norm_size < full_size / 3, (norm_size, full_size)

    # applying the denormalized form produces the same stored allocs
    # as applying the full form
    store2, _, _ = build_store()
    store.upsert_plan_results(result, "ev1")
    store2.upsert_plan_results(
        denormalize_plan_result(store2, norm), "ev1"
    )
    for i in (0, 1, 2):
        a1 = store.alloc_by_id(f"alloc-{i}")
        a2 = store2.alloc_by_id(f"alloc-{i}")
        assert a1.desired_status == a2.desired_status
        assert a1.desired_description == a2.desired_description
        assert a1.client_status == a2.client_status
        assert (
            a1.preempted_by_allocation == a2.preempted_by_allocation
        )

    # a diff whose alloc vanished locally is dropped, not an error
    empty = StateStore()
    ghost = denormalize_plan_result(empty, norm)
    assert ghost.node_update == {} and ghost.node_preemptions == {}


def test_stops_replicate_normalized(cluster):
    """A job scale-down's stops travel the raft log as diffs and every
    follower converges to the stopped state."""
    leader = cluster.wait_for_leader()
    register_capacity(leader)
    job = mock.job(id="shrink")
    job.task_groups[0].count = 3
    leader.register_job(job)
    assert leader.drain_to_idle(timeout=10.0)
    job2 = mock.job(id="shrink")
    job2.task_groups[0].count = 1
    job2.version = 1
    leader.register_job(job2)
    assert leader.drain_to_idle(timeout=10.0)
    live = [
        a
        for a in leader.store.allocs_by_job("default", "shrink")
        if not a.terminal_status()
    ]
    assert len(live) == 1
    stopped = [
        a
        for a in leader.store.allocs_by_job("default", "shrink")
        if a.desired_status == "stop"
    ]
    assert len(stopped) == 2
    for f in cluster.followers():
        wait_until(
            lambda f=f: {
                a.id
                for a in f.fsm.store.allocs_by_job("default", "shrink")
                if a.desired_status == "stop"
            }
            == {a.id for a in stopped},
            msg=f"stop replication to {f.addr}",
        )


def test_write_via_follower_forwards_to_leader(cluster):
    leader = cluster.wait_for_leader()
    register_capacity(leader)
    follower = cluster.followers()[0]
    job = mock.job(id="fwd")
    # the plain API call on a follower forwards to the leader, which
    # creates AND routes the eval (broker only runs there)
    follower.register_job(job)
    assert leader.drain_to_idle(timeout=10.0)
    assert len(leader.store.allocs_by_job("default", "fwd")) == 10

    # heartbeats through a follower arm the leader's TTL timers
    node = mock.node()
    follower.register_node(node)
    follower.heartbeat(node.id)
    assert node.id in leader._heartbeat_deadlines
    assert node.id not in follower._heartbeat_deadlines


def test_leader_failover_keeps_scheduling(cluster):
    leader = cluster.wait_for_leader()
    nodes = register_capacity(leader)
    job = mock.job(id="before")
    leader.register_job(job)
    assert leader.drain_to_idle(timeout=10.0)

    # kill the leader outright
    leader.stop()
    cluster.transport.set_down(leader.addr)
    rest = [s for s in cluster.servers if s is not leader]
    new_leader = None
    deadline = time.monotonic() + 8.0
    while time.monotonic() < deadline:
        est = [s for s in rest if s.is_leader() and s._leader_established]
        if est:
            new_leader = est[0]
            break
        time.sleep(0.02)
    assert new_leader is not None, "no new leader established"

    # the replicated state survived: old allocs visible
    assert len(new_leader.store.allocs_by_job("default", "before")) == 10
    # and the new leader schedules new work
    job2 = mock.job(id="after")
    new_leader.register_job(job2)
    assert new_leader.drain_to_idle(timeout=10.0)
    assert len(new_leader.store.allocs_by_job("default", "after")) == 10


def test_scheduler_config_replicates(cluster):
    leader = cluster.wait_for_leader()
    cfg = SchedulerConfiguration(scheduler_algorithm="spread")
    leader.store.set_scheduler_config(cfg)
    for f in cluster.followers():
        wait_until(
            lambda f=f: f.fsm.store.get_scheduler_config().scheduler_algorithm
            == "spread",
            msg="config replication",
        )


def test_follower_has_no_leader_services(cluster):
    leader = cluster.wait_for_leader()
    for f in cluster.followers():
        assert not f._leader_established
        assert not f.broker.enabled
    assert leader._leader_established
    assert leader.broker.enabled


def test_acl_replication(cluster):
    leader = cluster.wait_for_leader()
    from nomad_tpu.acl import Policy

    token = leader.acls.bootstrap()
    policy = Policy.from_dict(
        "readonly", {"namespace": {"default": {"policy": "read"}}}
    )
    leader.acls.upsert_policy(policy)
    for f in cluster.followers():
        wait_until(
            lambda f=f: "readonly" in f.fsm.acls.policies
            and token.accessor_id in f.fsm.acls.tokens_by_accessor,
            msg="acl replication",
        )
