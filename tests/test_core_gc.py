"""Core GC + periodic dispatch tests (reference model:
nomad/core_sched_test.go, nomad/periodic_test.go).
"""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server
from nomad_tpu.server.periodic import next_cron_launch
from nomad_tpu.structs import Periodic


def wait_until(cond, timeout=10.0, interval=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def server():
    s = Server(num_schedulers=1, heartbeat_ttl=60.0, seed=21)
    s.periodic.interval = 0.05
    s.start()
    yield s
    s.stop()


def test_force_gc_reaps_dead_job(server):
    for _ in range(2):
        server.register_node(mock.node())
    job = mock.batch_job()
    job.task_groups[0].count = 1
    server.register_job(job)
    assert server.drain_to_idle(10)
    allocs = server.store.allocs_by_job(job.namespace, job.id)
    for a in allocs:
        a.client_status = "complete"
    server.store.upsert_allocs(allocs)
    server.deregister_job(job.namespace, job.id)
    assert server.drain_to_idle(10)

    server.force_gc()
    assert server.drain_to_idle(10)
    assert wait_until(
        lambda: server.store.job_by_id(job.namespace, job.id) is None
    )
    assert not server.store.allocs_by_job(job.namespace, job.id)


def test_gc_spares_live_jobs(server):
    for _ in range(2):
        server.register_node(mock.node())
    job = mock.job()
    job.task_groups[0].count = 1
    server.register_job(job)
    assert server.drain_to_idle(10)
    server.force_gc()
    assert server.drain_to_idle(10)
    time.sleep(0.2)
    assert server.store.job_by_id(job.namespace, job.id) is not None
    assert server.store.allocs_by_job(job.namespace, job.id)


def test_node_gc_reaps_down_nodes(server):
    n = mock.node()
    server.register_node(n)
    server.update_node_status(n.id, "down")
    server.force_gc()
    assert server.drain_to_idle(10)
    assert wait_until(lambda: server.store.node_by_id(n.id) is None)


def test_next_cron_launch():
    # every minute
    base = time.mktime((2026, 7, 29, 12, 0, 30, 0, 0, -1))
    nxt = next_cron_launch("* * * * *", base)
    assert nxt is not None
    assert 0 < nxt - base <= 60
    # every 5 minutes
    nxt5 = next_cron_launch("*/5 * * * *", base)
    assert time.localtime(nxt5).tm_min % 5 == 0
    # specific hour
    nxt_h = next_cron_launch("0 3 * * *", base)
    tm = time.localtime(nxt_h)
    assert tm.tm_hour == 3 and tm.tm_min == 0
    assert next_cron_launch("bogus", base) is None


def test_periodic_job_launches_children(server):
    for _ in range(2):
        server.register_node(mock.node())
    job = mock.batch_job()
    job.task_groups[0].count = 1
    job.periodic = Periodic(enabled=True, spec="* * * * *")
    server.register_job(job)
    # no eval for the parent itself
    assert not server.store.evals_by_job(job.namespace, job.id)
    # force a launch rather than waiting a minute
    child = server.periodic.force_launch(job)
    assert child.parent_id == job.id
    assert child.id.startswith(job.id + "/periodic-")
    assert server.drain_to_idle(10)
    assert server.store.allocs_by_job(child.namespace, child.id)


def test_periodic_prohibit_overlap(server):
    for _ in range(1):
        server.register_node(mock.node())
    job = mock.batch_job()
    job.task_groups[0].count = 1
    job.periodic = Periodic(
        enabled=True, spec="* * * * *", prohibit_overlap=True
    )
    server.register_job(job)
    child = server.periodic.force_launch(job)
    assert server.drain_to_idle(10)
    # with the child pending/running, the overlap guard reports busy
    assert server.periodic._has_running_child(job)
